"""Blockwise int8 quantize/dequantize for gradient wire compression.

Reference context: NVIDIA Apex ships no gradient compression — its DDP
moves fp16/fp32 buckets (``apex/parallel/distributed.py:425-470``) and its
only wire narrowing is the ZeRO ``e5m2_allgather`` param transport. EQuARX
(arxiv 2506.17615) shows blockwise-quantized AllReduce inside XLA recovers
near-full quality at a fraction of the interconnect bytes; this module is
the codec half of that design: flat fp buffers are split into fixed-size
blocks, each block carries one fp32 scale (absmax/127) and int8 mantissas —
4 bytes of scale overhead per ``block_size`` elements, so the wire cost is
``n + 4n/B`` bytes vs ``4n`` for fp32 (≈3.9× at B=256).

Two implementations with identical deterministic math:

* pure JAX (reshape → absmax → round → clip): XLA fuses this into the
  surrounding program; always available, the ground truth for tests;
* a Pallas TPU kernel (``use_pallas``): one VMEM pass producing the int8
  codes and fp32 scales per row-block — selected automatically on compiled
  TPU backends for tile-aligned shapes, opt-in interpret mode elsewhere
  (the ``ops/layer_norm.py`` gating pattern).

Stochastic rounding (``stochastic=True``) draws one uniform per element and
rounds ``floor(x/scale + u)`` — unbiased (E[q·scale] = x), the standard
requirement for quantized *training* signals; the Pallas path uses the
on-core PRNG (``pltpu.prng_random_bits``), the JAX path ``jax.random``.
Both are deterministic given the seed, but their streams differ — parity
tests pin the deterministic mode.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from apex_tpu.ops._pallas_util import compiled_backend as _compiled_backend
from apex_tpu.ops._pallas_util import sds as _sds

try:  # keep import-failure graceful (CPU-only envs), like ops/layer_norm.py
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    _HAS_PALLAS = True
except Exception:  # pragma: no cover
    _HAS_PALLAS = False

QMAX = 127.0  # symmetric int8 code range; -128 is never emitted
QMAX4 = 7.0   # symmetric int4 code range; -8 is never emitted


def qmax_for_bits(bits: int) -> float:
    if bits == 8:
        return QMAX
    if bits == 4:
        return QMAX4
    raise ValueError(f"unsupported code width: {bits} bits")


def blocks_for(n: int, block_size: int) -> int:
    """Number of scale blocks covering ``n`` elements."""
    return -(-n // block_size)


def padded_size(n: int, block_size: int) -> int:
    return blocks_for(n, block_size) * block_size


def _block_scales(xb: jnp.ndarray, qmax: float = QMAX) -> jnp.ndarray:
    """(rows, block) fp32 -> (rows,) fp32 scale = absmax/qmax, with all-zero
    blocks mapped to scale 1 so the quotient is well-defined (codes are 0
    there anyway)."""
    amax = jnp.max(jnp.abs(xb), axis=1)
    return jnp.where(amax > 0, amax / qmax, 1.0)


def _uniform_from_bits(bits: jnp.ndarray) -> jnp.ndarray:
    """uint32 -> [0, 1) fp32 using the top 24 bits (exactly representable)."""
    return (bits >> jnp.uint32(8)).astype(jnp.float32) * jnp.float32(2.0**-24)


# ---------------------------------------------------------------------------
# int4 nibble packing. Codes live in [-7, 7]; two two's-complement nibbles
# share one byte (even index -> low nibble), so the packed wire/HBM payload
# is exactly 0.5 B per element. Pure elementwise bit ops — XLA fuses the
# pack/unpack into the surrounding program (and the Pallas paged-attention /
# megakernel paths inline the same unpack in-kernel).


def pack_int4(q: jnp.ndarray) -> jnp.ndarray:
    """int8 codes in [-7, 7], even-sized last axis -> uint8 packed pairs
    (last axis halved)."""
    if q.shape[-1] % 2:
        raise ValueError(f"pack_int4 needs an even last axis: {q.shape}")
    lo = q[..., 0::2].astype(jnp.uint8) & jnp.uint8(0xF)
    hi = q[..., 1::2].astype(jnp.uint8) & jnp.uint8(0xF)
    return lo | (hi << jnp.uint8(4))


def unpack_int4(packed: jnp.ndarray) -> jnp.ndarray:
    """uint8 packed pairs -> int8 codes (last axis doubled); exact inverse
    of :func:`pack_int4` for codes in [-8, 7]."""
    lo = (packed & jnp.uint8(0xF)).astype(jnp.int8)
    hi = (packed >> jnp.uint8(4)).astype(jnp.int8)
    lo = jnp.where(lo > 7, lo - 16, lo)
    hi = jnp.where(hi > 7, hi - 16, hi)
    return jnp.stack([lo, hi], axis=-1).reshape(*packed.shape[:-1],
                                                2 * packed.shape[-1])


# ---------------------------------------------------------------------------
# Pure-JAX reference path

def _quantize_jax(x_flat, block_size: int, stochastic: bool, seed,
                  qmax: float = QMAX):
    xb = x_flat.astype(jnp.float32).reshape(-1, block_size)
    scales = _block_scales(xb, qmax)
    y = xb / scales[:, None]
    if stochastic:
        key = jax.random.fold_in(jax.random.PRNGKey(0), seed)
        u = _uniform_from_bits(
            jax.random.bits(key, xb.shape, dtype=jnp.uint32))
        q = jnp.floor(y + u)
    else:
        q = jnp.round(y)
    q = jnp.clip(q, -qmax, qmax).astype(jnp.int8)
    return q.reshape(-1), scales


def _dequantize_jax(q_flat, scales, block_size: int):
    qb = q_flat.reshape(-1, block_size).astype(jnp.float32)
    return (qb * scales[:, None]).reshape(-1)


# ---------------------------------------------------------------------------
# Pallas kernels — one pass per row-block of (rows_per_step, block) elements

def _quant_kernel(x_ref, q_ref, s_ref, *, qmax=QMAX):
    x = x_ref[:].astype(jnp.float32)
    amax = jnp.max(jnp.abs(x), axis=1, keepdims=True)
    scale = jnp.where(amax > 0, amax / qmax, 1.0)
    q = jnp.clip(jnp.round(x / scale), -qmax, qmax)
    q_ref[:] = q.astype(jnp.int8)
    s_ref[:] = scale


def _quant_kernel_stochastic(x_ref, seed_ref, q_ref, s_ref, *, qmax=QMAX):
    # one PRNG stream per grid step: the per-core PRNG is reseeded with the
    # (seed, program_id) pair so every row-block draws independent bits
    pltpu.prng_seed(seed_ref[0], pl.program_id(0))
    x = x_ref[:].astype(jnp.float32)
    amax = jnp.max(jnp.abs(x), axis=1, keepdims=True)
    scale = jnp.where(amax > 0, amax / qmax, 1.0)
    y = x / scale
    bits = pltpu.bitcast(pltpu.prng_random_bits(y.shape), jnp.uint32)
    q = jnp.clip(jnp.floor(y + _uniform_from_bits(bits)), -qmax, qmax)
    q_ref[:] = q.astype(jnp.int8)
    s_ref[:] = scale


def _dequant_kernel(q_ref, s_ref, y_ref):
    y_ref[:] = (q_ref[:].astype(jnp.float32) * s_ref[:]).astype(y_ref.dtype)


# int8 VREG tiling wants (32, 128) blocks; a grid step holds a few fp32
# copies of the row block — keep it well under a core's VMEM
_ROWS_PER_STEP = 32


def _pallas_ok(n: int, block_size: int, allow_interpret: bool) -> bool:
    if not _HAS_PALLAS:
        return False
    if block_size % 128 != 0:
        return False
    rows = n // block_size
    if n % block_size != 0 or rows % _ROWS_PER_STEP != 0:
        return False
    return allow_interpret or _compiled_backend()


def _interpret_default() -> bool:
    return not _compiled_backend()


def _quantize_pallas(x_flat, block_size: int, stochastic: bool, seed,
                     qmax: float = QMAX):
    rows = x_flat.size // block_size
    x2d = x_flat.reshape(rows, block_size)
    grid = (rows // _ROWS_PER_STEP,)
    out_shape = [
        _sds((rows, block_size), jnp.int8, x_flat),
        _sds((rows, 1), jnp.float32, x_flat),
    ]
    out_specs = [
        pl.BlockSpec((_ROWS_PER_STEP, block_size), lambda i: (i, 0)),
        pl.BlockSpec((_ROWS_PER_STEP, 1), lambda i: (i, 0)),
    ]
    x_spec = pl.BlockSpec((_ROWS_PER_STEP, block_size), lambda i: (i, 0))
    if stochastic:
        q, s = pl.pallas_call(
            functools.partial(_quant_kernel_stochastic, qmax=qmax),
            grid=grid,
            in_specs=[
                x_spec,
                pl.BlockSpec(memory_space=pltpu.SMEM),
            ],
            out_specs=out_specs,
            out_shape=out_shape,
            interpret=_interpret_default(),
        )(x2d, jnp.asarray(seed, jnp.int32).reshape((1,)))
    else:
        q, s = pl.pallas_call(
            functools.partial(_quant_kernel, qmax=qmax),
            grid=grid,
            in_specs=[x_spec],
            out_specs=out_specs,
            out_shape=out_shape,
            interpret=_interpret_default(),
        )(x2d)
    return q.reshape(-1), s.reshape(-1)


def _dequantize_pallas(q_flat, scales, block_size: int):
    rows = q_flat.size // block_size
    y = pl.pallas_call(
        _dequant_kernel,
        grid=(rows // _ROWS_PER_STEP,),
        in_specs=[
            pl.BlockSpec((_ROWS_PER_STEP, block_size), lambda i: (i, 0)),
            pl.BlockSpec((_ROWS_PER_STEP, 1), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((_ROWS_PER_STEP, block_size),
                               lambda i: (i, 0)),
        out_shape=_sds((rows, block_size), jnp.float32, q_flat, scales),
        interpret=_interpret_default(),
    )(q_flat.reshape(rows, block_size), scales.reshape(rows, 1))
    return y.reshape(-1)


# ---------------------------------------------------------------------------
# Public API

def quantize_blockwise(
    x_flat: jnp.ndarray,
    block_size: int = 256,
    stochastic: bool = False,
    seed=None,
    use_pallas: Optional[bool] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Flat fp buffer -> (int8 codes (n,), fp32 per-block scales (n/B,)).

    ``x_flat.size`` must be a multiple of ``block_size`` (callers pad; see
    :func:`padded_size`). ``seed``: int32 scalar, required when
    ``stochastic`` — the codes are deterministic given it.
    """
    if x_flat.ndim != 1:
        raise ValueError(f"expected flat buffer, got shape {x_flat.shape}")
    if x_flat.size % block_size != 0:
        raise ValueError(
            f"size {x_flat.size} not a multiple of block_size {block_size}")
    if stochastic and seed is None:
        raise ValueError("stochastic quantization needs a seed")
    if use_pallas is None:
        use_pallas = _pallas_ok(x_flat.size, block_size,
                                allow_interpret=False)
    elif use_pallas and not _pallas_ok(x_flat.size, block_size,
                                       allow_interpret=True):
        raise ValueError(
            f"pallas quantize needs block_size % 128 == 0 and "
            f"rows % {_ROWS_PER_STEP} == 0; got n={x_flat.size}, "
            f"block_size={block_size}")
    if stochastic and use_pallas and _interpret_default():
        # pltpu.prng_* has no CPU interpreter lowering — the stochastic
        # kernel is compiled-Mosaic-only; off-TPU the JAX stream stands in
        # (different bits, same distribution — parity tests pin the
        # deterministic mode)
        use_pallas = False
    if use_pallas:
        return _quantize_pallas(x_flat, block_size, stochastic, seed)
    return _quantize_jax(x_flat, block_size, stochastic, seed)


def dequantize_blockwise(
    q_flat: jnp.ndarray,
    scales: jnp.ndarray,
    block_size: int = 256,
    use_pallas: Optional[bool] = None,
) -> jnp.ndarray:
    """(int8 codes, fp32 scales) -> fp32 flat buffer."""
    if q_flat.size % block_size != 0:
        raise ValueError(
            f"size {q_flat.size} not a multiple of block_size {block_size}")
    if use_pallas is None:
        use_pallas = _pallas_ok(q_flat.size, block_size,
                                allow_interpret=False)
    elif use_pallas and not _pallas_ok(q_flat.size, block_size,
                                       allow_interpret=True):
        raise ValueError(
            f"pallas dequantize needs block_size % 128 == 0 and "
            f"rows % {_ROWS_PER_STEP} == 0; got n={q_flat.size}, "
            f"block_size={block_size}")
    if use_pallas:
        return _dequantize_pallas(q_flat, scales, block_size)
    return _dequantize_jax(q_flat, scales, block_size)


@functools.partial(jax.jit, static_argnums=(1,))
def quantization_error(x_flat, block_size: int = 256):
    """Round-trip error ``x - dq(q(x))`` of the deterministic codec — the
    quantity error feedback re-injects (``error_feedback.py``)."""
    q, s = quantize_blockwise(x_flat, block_size)
    return x_flat.astype(jnp.float32) - dequantize_blockwise(q, s, block_size)


# ---------------------------------------------------------------------------
# 4-bit group-quantized codec. Same scale/rounding machinery at the ±7 code
# range (one fp32 scale per ``group_size`` elements — "group" is the sub-8-
# bit literature's name for the int8 codec's "block"), with the codes
# nibble-packed two per byte: the wire/HBM payload is ``n/2 + 4·n/G`` bytes
# vs ``4n`` fp32 (≈7.5× at G=128). The rounding (incl. the stochastic
# Pallas path — on-core PRNG) happens in the shared kernels; the pack is a
# fused elementwise bit op.


def quantize_blockwise_int4(
    x_flat: jnp.ndarray,
    group_size: int = 128,
    stochastic: bool = False,
    seed=None,
    use_pallas: Optional[bool] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Flat fp buffer -> (packed uint8 codes (n/2,), fp32 per-group scales
    (n/G,)). ``x_flat.size`` must be a multiple of the (even) group size;
    ``seed`` as in :func:`quantize_blockwise`."""
    if x_flat.ndim != 1:
        raise ValueError(f"expected flat buffer, got shape {x_flat.shape}")
    if group_size % 2:
        raise ValueError(
            f"int4 group_size must be even (nibble packing): {group_size}")
    if x_flat.size % group_size != 0:
        raise ValueError(
            f"size {x_flat.size} not a multiple of group_size {group_size}")
    if stochastic and seed is None:
        raise ValueError("stochastic quantization needs a seed")
    if use_pallas is None:
        use_pallas = _pallas_ok(x_flat.size, group_size,
                                allow_interpret=False)
    elif use_pallas and not _pallas_ok(x_flat.size, group_size,
                                       allow_interpret=True):
        raise ValueError(
            f"pallas int4 quantize needs group_size % 128 == 0 and "
            f"rows % {_ROWS_PER_STEP} == 0; got n={x_flat.size}, "
            f"group_size={group_size}")
    if stochastic and use_pallas and _interpret_default():
        use_pallas = False  # pltpu.prng_* is compiled-Mosaic-only
    if use_pallas:
        q, s = _quantize_pallas(x_flat, group_size, stochastic, seed,
                                qmax=QMAX4)
    else:
        q, s = _quantize_jax(x_flat, group_size, stochastic, seed,
                             qmax=QMAX4)
    return pack_int4(q), s


def dequantize_blockwise_int4(
    packed: jnp.ndarray,
    scales: jnp.ndarray,
    group_size: int = 128,
    use_pallas: Optional[bool] = None,
) -> jnp.ndarray:
    """(packed uint8 codes, fp32 group scales) -> fp32 flat buffer."""
    q = unpack_int4(packed)
    return dequantize_blockwise(q, scales, group_size, use_pallas=use_pallas)


@functools.partial(jax.jit, static_argnums=(1,))
def quantization_error_int4(x_flat, group_size: int = 128):
    """Round-trip error of the deterministic int4 codec (the EF residual
    quantity for the ``int4_ef`` policy)."""
    q, s = quantize_blockwise_int4(x_flat, group_size)
    return x_flat.astype(jnp.float32) - dequantize_blockwise_int4(
        q, s, group_size)

"""Input pipeline (native-threaded prefetcher + normalize).

Reference analogue: the imagenet example's CUDA-stream ``data_prefetcher``
(``examples/imagenet/main_amp.py:265``) — overlap batch assembly and
normalization with the training step. Here the host side runs in the C++
core (``apex_tpu/_native``); device transfer overlap comes from
``jax.device_put`` on the next batch while the current step executes.
"""

from apex_tpu.data.loader import BatchLoader, normalize_u8  # noqa: F401
from apex_tpu.data.prefetch import prefetch_to_device  # noqa: F401

__all__ = ["BatchLoader", "normalize_u8", "prefetch_to_device"]

"""Host→device prefetch: keep batches in flight ahead of the train step.

Reference analogue: the imagenet example's ``data_prefetcher``
(``examples/imagenet/main_amp.py:256-300``) — a side CUDA stream that
uploads and normalizes the NEXT batch while the current step computes.
On TPU the side stream is jax's async dispatch: ``jax.device_put`` returns
immediately and the transfer rides the infeed DMA, so a small deque of
in-flight batches gives the same overlap with no stream management.
"""

from __future__ import annotations

import collections
import itertools
from typing import Any, Iterable, Iterator, Optional

import jax

Pytree = Any


def prefetch_to_device(
    iterator: Iterable[Pytree],
    size: int = 2,
    sharding: Optional[Any] = None,
) -> Iterator[Pytree]:
    """Yield batches from ``iterator`` with ``size`` of them already
    submitted to the device.

    ``sharding``: optional ``jax.sharding.Sharding`` (e.g.
    ``NamedSharding(mesh, P("dp", ...))``) applied to every leaf — the
    batch lands pre-sharded over the mesh, so the jitted step consumes it
    without a resharding copy. With ``size >= 2`` the (i+1)-th transfer
    overlaps the i-th step's compute (the reference prefetcher's
    double-buffering).

    The generator is closeable: a consumer that breaks early (or whose
    ``for`` loop is garbage-collected) triggers ``close()``, and the
    ``finally`` block drops the ``size`` still-in-flight device batches —
    without it every early exit strands ``size`` batches of device memory
    until the generator object dies.
    """
    if size < 1:
        raise ValueError(f"size must be >= 1, got {size}")
    it = iter(iterator)
    queue: collections.deque = collections.deque()

    def submit(n: int) -> None:
        for batch in itertools.islice(it, n):
            if sharding is None:
                queue.append(jax.tree.map(jax.device_put, batch))
            else:
                queue.append(jax.tree.map(
                    lambda x: jax.device_put(x, sharding), batch))

    try:
        submit(size)
        while queue:
            out = queue.popleft()
            submit(1)
            yield out
    finally:
        # early break / close(): release the in-flight transfers. The
        # arrays may still be mid-DMA — dropping the references is enough;
        # the backend frees each buffer once its transfer lands.
        queue.clear()

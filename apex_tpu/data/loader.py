"""ctypes driver for the native batch-assembly core (see package doc)."""

from __future__ import annotations

import ctypes
from typing import Iterator, Optional, Sequence

import numpy as np

from apex_tpu._native import build_lib


def normalize_u8(images_u8: np.ndarray, mean: Sequence[float],
                 std: Sequence[float], n_threads: int = 4) -> np.ndarray:
    """(…, C) uint8 -> float32 ``(x/255 - mean)/std`` in C++ threads
    (numpy fallback when the toolchain is unavailable)."""
    assert images_u8.dtype == np.uint8
    c = images_u8.shape[-1]
    assert len(mean) == c and len(std) == c
    lib = build_lib()
    src = np.ascontiguousarray(images_u8)
    if lib is None:
        return ((src.astype(np.float32) / 255.0
                 - np.asarray(mean, np.float32))
                / np.asarray(std, np.float32))
    dst = np.empty(src.shape, np.float32)
    m = (ctypes.c_float * c)(*[float(x) for x in mean])
    s = (ctypes.c_float * c)(*[float(x) for x in std])
    lib.al_normalize_u8_f32(
        src.ctypes.data_as(ctypes.c_void_p),
        dst.ctypes.data_as(ctypes.c_void_p),
        src.size // c, c, m, s, n_threads)
    return dst


class BatchLoader:
    """Threaded gather of sample rows into batches with one-deep pipelining.

    ``source``: (N, ...) array of samples (any dtype, C-contiguous).
    ``iterate(index_batches)`` yields assembled batches while the NEXT one is
    being built by the worker threads — the prefetcher overlap.
    """

    def __init__(self, source: np.ndarray, n_workers: int = 2):
        self.source = np.ascontiguousarray(source)
        self.item_shape = self.source.shape[1:]
        self.item_bytes = int(self.source[0].nbytes) if len(source) else 0
        self._lib = build_lib()
        self._handle = None
        if self._lib is not None:
            self._handle = self._lib.al_create(
                self.source.ctypes.data_as(ctypes.c_void_p),
                len(self.source), self.item_bytes, n_workers, 4)

    def _submit(self, indices: np.ndarray, out: np.ndarray) -> int:
        idx = np.ascontiguousarray(indices, np.int64)
        return self._lib.al_submit(
            self._handle,
            idx.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            len(idx), out.ctypes.data_as(ctypes.c_void_p))

    def gather(self, indices: np.ndarray) -> np.ndarray:
        """Blocking single-batch assembly."""
        if self._handle is None:
            return self.source[np.asarray(indices)]
        out = np.empty((len(indices),) + self.item_shape, self.source.dtype)
        ticket = self._submit(indices, out)
        rc = self._lib.al_wait(self._handle, ticket)
        if rc != 0:
            raise IndexError("batch indices out of range")
        return out

    def iterate(self, index_batches) -> Iterator[np.ndarray]:
        """Pipelined iteration: batch k+1 assembles while k is consumed."""
        if self._handle is None:
            for idx in index_batches:
                yield self.source[np.asarray(idx)]
            return
        pending = None  # (ticket, out) — out must outlive the ticket
        try:
            for idx in index_batches:
                out = np.empty((len(idx),) + self.item_shape,
                               self.source.dtype)
                ticket = self._submit(np.asarray(idx), out)
                prev, pending = pending, (ticket, out)
                if prev is not None:
                    p_ticket, p_out = prev
                    if self._lib.al_wait(self._handle, p_ticket) != 0:
                        raise IndexError("batch indices out of range")
                    yield p_out
            if pending is not None:
                p_ticket, p_out = pending
                pending = None
                if self._lib.al_wait(self._handle, p_ticket) != 0:
                    raise IndexError("batch indices out of range")
                yield p_out
        finally:
            # Consumer abandoned the generator (break / GeneratorExit) or an
            # index error fired while a worker was still memcpy-ing into the
            # in-flight buffer: block until it settles so `out` cannot be
            # freed under the worker's feet.
            if pending is not None:
                self._lib.al_wait(self._handle, pending[0])

    def close(self):
        if self._handle is not None and self._lib is not None:
            self._lib.al_destroy(self._handle)
            self._handle = None

    def __del__(self):  # pragma: no cover - destructor timing
        try:
            self.close()
        except Exception:
            pass

"""Weight normalization reparameterization (ref ``apex/reparameterization``).

Reference: ``apply_weight_norm`` (``reparameterization/__init__.py:4``) +
``WeightNorm``/``Reparameterization`` — forward pre-hooks that recompute
``w = g * v / ||v||`` before every forward.

TPU re-design: the hook machinery becomes two pure functions over the param
pytree — decompose once, recompose inside the (jitted) forward; XLA fuses
the norm into the consumer. ``dim=0`` matches the reference default (norm
over all dims except the first / output dim — for flax kernels of shape
(in, out) pass ``dim=-1``).
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp

Pytree = Any
_EPS = 1e-12


def _norm_except(v, dim: int):
    axes = tuple(a for a in range(v.ndim) if a != dim % v.ndim)
    return jnp.sqrt(jnp.sum(jnp.square(v.astype(jnp.float32)), axis=axes,
                            keepdims=True))


def apply_weight_norm(params: Pytree, name_filter: Optional[Callable] = None,
                      dim: int = 0) -> Pytree:
    """Decompose selected weights into ``{"g", "v"}`` (ref
    ``apply_weight_norm``). ``name_filter(path_str)`` selects leaves
    (default: every float leaf with ndim >= 2)."""
    from apex_tpu.amp.frontend import _path_str

    def leaf(path, x):
        p = _path_str(path)
        sel = (name_filter(p) if name_filter is not None
               else (hasattr(x, "ndim") and x.ndim >= 2
                     and jnp.issubdtype(jnp.result_type(x), jnp.floating)))
        if not sel:
            return x
        g = _norm_except(x, dim).astype(x.dtype)
        return {"wn_g": g, "wn_v": x}

    return jax.tree_util.tree_map_with_path(leaf, params)


def remove_weight_norm(params: Pytree, dim: int = 0) -> Pytree:
    """Recompose ``w = g * v/||v||`` (ref ``remove_weight_norm``); the
    inverse of :func:`apply_weight_norm`. Call inside the forward so the
    norm is recomputed each step (the pre-hook semantics)."""

    def is_wn(x):
        return isinstance(x, dict) and set(x.keys()) == {"wn_g", "wn_v"}

    def leaf(x):
        if not is_wn(x):
            return x
        v = x["wn_v"]
        return (x["wn_g"].astype(jnp.float32)
                * v.astype(jnp.float32)
                / (_norm_except(v, dim) + _EPS)).astype(v.dtype)

    return jax.tree_util.tree_map(leaf, params, is_leaf=is_wn)

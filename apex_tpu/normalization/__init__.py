"""Fused normalization modules (L3) — ref ``apex/normalization/__init__.py``."""

from apex_tpu.normalization.fused_layer_norm import (  # noqa: F401
    FusedLayerNorm,
    FusedRMSNorm,
    MixedFusedLayerNorm,
    MixedFusedRMSNorm,
)
from apex_tpu.ops.layer_norm import layer_norm, rms_norm  # noqa: F401

__all__ = [
    "FusedLayerNorm",
    "FusedRMSNorm",
    "MixedFusedLayerNorm",
    "MixedFusedRMSNorm",
    "layer_norm",
    "rms_norm",
]

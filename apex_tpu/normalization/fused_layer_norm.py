"""FusedLayerNorm / FusedRMSNorm flax modules.

Reference: ``apex/normalization/fused_layer_norm.py`` — ``FusedLayerNorm``
(``:204``), ``FusedRMSNorm`` (``:300``), mixed-dtype Megatron variants
(``MixedFusedLayerNorm``/``MixedFusedRMSNorm``, ``:398,420``), each binding
``fused_layer_norm_cuda`` with a CPU fallback. Here the modules wrap the
Pallas/XLA kernels in :mod:`apex_tpu.ops.layer_norm`; "mixed" means params are
created fp32 and the computation runs fp32 regardless of input dtype, with the
output cast back to the input dtype (the Megatron convention).
"""

from __future__ import annotations

from typing import Optional, Sequence, Union

import flax.linen as nn
import jax.numpy as jnp

from apex_tpu.ops.layer_norm import layer_norm, rms_norm


def _norm_shape(shape: Union[int, Sequence[int]]):
    if isinstance(shape, int):
        return (shape,)
    return tuple(shape)


class FusedLayerNorm(nn.Module):
    """Layer norm over the trailing ``normalized_shape`` dims
    (ref ``fused_layer_norm.py:204-298``)."""

    normalized_shape: Union[int, Sequence[int]]
    eps: float = 1e-5
    elementwise_affine: bool = True
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        shape = _norm_shape(self.normalized_shape)
        hidden = 1
        for s in shape:
            hidden *= s
        lead = x.shape[: len(x.shape) - len(shape)]
        x2 = x.reshape(lead + (hidden,))
        if self.elementwise_affine:
            w = self.param("scale", nn.initializers.ones, (hidden,), self.param_dtype)
            b = self.param("bias", nn.initializers.zeros, (hidden,), self.param_dtype)
        else:
            w = b = None
        y = layer_norm(x2, w, b, self.eps)
        return y.reshape(x.shape)


class FusedRMSNorm(nn.Module):
    """RMS norm (ref ``fused_layer_norm.py:300-396``)."""

    normalized_shape: Union[int, Sequence[int]]
    eps: float = 1e-5
    elementwise_affine: bool = True
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        shape = _norm_shape(self.normalized_shape)
        hidden = 1
        for s in shape:
            hidden *= s
        lead = x.shape[: len(x.shape) - len(shape)]
        x2 = x.reshape(lead + (hidden,))
        if self.elementwise_affine:
            w = self.param("scale", nn.initializers.ones, (hidden,), self.param_dtype)
        else:
            w = None
        y = rms_norm(x2, w, self.eps)
        return y.reshape(x.shape)


class MixedFusedLayerNorm(FusedLayerNorm):
    """Megatron mixed-dtype variant (ref ``fused_layer_norm.py:398-418``):
    fp32 params + fp32 math with bf16/fp16 I/O. The base kernels already
    compute in fp32 and return x.dtype, so this is the base class with the
    param dtype pinned fp32 — kept as a distinct name for API parity."""

    param_dtype: jnp.dtype = jnp.float32


class MixedFusedRMSNorm(FusedRMSNorm):
    """Ref ``fused_layer_norm.py:420-438``."""

    param_dtype: jnp.dtype = jnp.float32

"""Timers with optional cross-device aggregation.

Reference: Megatron ``_Timers`` (``apex/transformer/pipeline_parallel/_timers.py:6-83``)
— named start/stop wall timers, log with optional ``torch.distributed`` max/min
normalization. TPU notes: device work is async, so each stop() blocks on
``jax.block_until_ready``-style sync only if asked; aggregation across hosts
uses a tiny jitted psum when a mesh is initialized.
"""

from __future__ import annotations

import time
from typing import Dict, Iterable, Optional

import jax


class _Timer:
    def __init__(self, name: str):
        self.name = name
        self.elapsed_ = 0.0
        self.started_ = False
        self.start_time = 0.0

    def start(self, sync: bool = False):
        assert not self.started_, f"timer {self.name} already started"
        if sync:
            _sync_devices()
        self.start_time = time.perf_counter()
        self.started_ = True

    def stop(self, sync: bool = False):
        assert self.started_, f"timer {self.name} not started"
        if sync:
            _sync_devices()
        self.elapsed_ += time.perf_counter() - self.start_time
        self.started_ = False

    def reset(self):
        self.elapsed_ = 0.0
        self.started_ = False

    def elapsed(self, reset: bool = True) -> float:
        started = self.started_
        if started:
            self.stop()
        out = self.elapsed_
        if reset:
            self.reset()
        if started:
            self.start()
        return out


def _sync_devices():
    # Barrier on all outstanding device work: the TPU analogue of
    # torch.cuda.synchronize() in _timers.py:30.
    (jax.device_put(0.0) + 0).block_until_ready()


class Timers:
    """Group of named timers (ref _timers.py:40-83)."""

    def __init__(self):
        self.timers: Dict[str, _Timer] = {}

    def __call__(self, name: str) -> _Timer:
        if name not in self.timers:
            self.timers[name] = _Timer(name)
        return self.timers[name]

    def write(self, names: Iterable[str], iteration: int, normalizer: float = 1.0):
        for name in names:
            value = self.timers[name].elapsed(reset=False) / normalizer
            print(f"timers/{name} @ {iteration}: {value:.6f}s")

    def log(
        self,
        names: Optional[Iterable[str]] = None,
        normalizer: float = 1.0,
        reset: bool = True,
    ) -> str:
        assert normalizer > 0.0
        names = list(names) if names is not None else list(self.timers)
        string = "time (ms)"
        for name in names:
            t = self.timers[name].elapsed(reset=reset) * 1000.0 / normalizer
            string += f" | {name}: {t:.2f}"
        return string

from apex_tpu.utils.timers import Timers, _Timer  # noqa: F401

__all__ = ["Timers"]

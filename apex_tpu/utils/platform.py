"""Platform pinning for CPU/virtual-mesh execution.

The image's axon sitecustomize registers the TPU-tunnel backend for every
interpreter; setting ``JAX_PLATFORMS=cpu`` in the environment does NOT stop
the hook from initializing (and possibly dialing) that backend — only the
``jax_platforms`` config flag does. Every CPU-bound entry point (tests,
virtual-mesh benchmarks, baseline generators) should call
:func:`pin_cpu_platform` before first device use instead of re-deriving
this recipe.
"""

from __future__ import annotations

import os


_PROBE_CACHE_TTL_S = 600
# a dead verdict goes stale fast: a tunnel that just revived must not keep
# benching on the CPU-fallback path for ten minutes
_PROBE_CACHE_DEAD_TTL_S = 60


def _probe_cache_path() -> str:
    """Per-boot, per-user cache file for the probe verdict. The boot id
    keys it so a stale file from a previous machine boot can never answer;
    the uid keeps the path out of reach of other users on a shared host
    (advisor r3: a world-shared /tmp name could be pre-created or
    symlinked by another user)."""
    import tempfile

    try:
        with open("/proc/sys/kernel/random/boot_id") as f:
            boot = f.read().strip().replace("-", "")
    except OSError:
        boot = "noboot"
    uid = os.getuid() if hasattr(os, "getuid") else 0
    return os.path.join(tempfile.gettempdir(),
                        f"apex_tpu_probe_u{uid}_{boot}")


def probe_backend(timeout_s: int = 240) -> int:
    """Device count of the default backend, probed in a KILLABLE
    subprocess; 0 when init hangs or fails. The axon tunnel blocks forever
    inside backend init when its relay is down (observed in round 2) — a
    parent process's own first backend touch would hang with it, so this
    is the only safe way to ask. Healthy-platform cost: one extra backend
    dial in the child (~tens of seconds on a tunnel); a dead tunnel costs
    the full timeout once.

    The verdict is cached on disk for ``_PROBE_CACHE_TTL_S`` (keyed by
    machine boot id) so back-to-back entry points — bench.py, then
    bench_matrix's five configs — pay the extra backend dial once per
    session, not once per process. Set ``APEX_TPU_PROBE_NO_CACHE=1`` to
    force a fresh probe (e.g. when waiting for a dead tunnel to revive).

    When this process has ALREADY initialized its backends, asking jax
    directly is hang-safe and also sidesteps exclusive-device locks the
    child could trip over (e.g. the driver holding the TPU after
    ``entry()``) — do that instead of spawning.
    """
    import subprocess
    import sys
    import time

    try:
        from jax._src import xla_bridge

        if xla_bridge.backends_are_initialized():
            import jax

            return len(jax.devices())
    except (ImportError, AttributeError):
        pass  # fall through to the subprocess probe

    cache = _probe_cache_path()
    use_cache = os.environ.get("APEX_TPU_PROBE_NO_CACHE") != "1"
    if use_cache:
        try:
            age = time.time() - os.path.getmtime(cache)
            with open(cache) as f:
                cached = int(f.read().strip())
            ttl = _PROBE_CACHE_TTL_S if cached else _PROBE_CACHE_DEAD_TTL_S
            if age < ttl:
                return cached
        except (OSError, ValueError):
            pass

    code = ("import jax, jax.numpy as jnp; "
            "x = jnp.ones((128, 128), jnp.bfloat16); "
            "assert float((x @ x).sum()) > 0; "
            "print(len(jax.devices()))")
    try:
        proc = subprocess.run([sys.executable, "-c", code],
                              capture_output=True, text=True,
                              timeout=timeout_s)
        if proc.returncode != 0:
            verdict = 0
        else:
            verdict = int(proc.stdout.strip().splitlines()[-1])
    except (subprocess.TimeoutExpired, ValueError, IndexError):
        verdict = 0
    if use_cache:
        try:
            # atomic rename of a private temp file: concurrent probers
            # never see a half-written verdict, and an attacker-placed
            # symlink at the final path is replaced, not followed
            import tempfile as _tf

            fd, tmp = _tf.mkstemp(dir=os.path.dirname(cache),
                                  prefix=".apex_tpu_probe_")
            with os.fdopen(fd, "w") as f:
                f.write(str(verdict))
            os.replace(tmp, cache)
        except OSError:
            pass
    return verdict


def pin_cpu_platform(virtual_devices: int | None = None) -> None:
    """Force the CPU backend; optionally expose ``virtual_devices`` host
    devices (the multi-chip simulation used across the test suite).

    Call before the first jax backend use. Safe to call multiple times;
    an existing ``xla_force_host_platform_device_count`` flag is kept.
    """
    os.environ["JAX_PLATFORMS"] = "cpu"
    if virtual_devices is not None:
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + f" --xla_force_host_platform_device_count="
                f"{virtual_devices}").strip()
    import jax

    jax.config.update("jax_platforms", "cpu")


def pin_cpu_if_requested() -> None:
    """CLI entry-point preamble: honor an explicit ``JAX_PLATFORMS=cpu``
    request. The axon sitecustomize hook ignores the env var alone — only
    the jax config flag keeps the process off the tunnel — so every
    benchmark script calls this before its first jax backend use instead
    of re-deriving the recipe."""
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        pin_cpu_platform()


def pin_cpu_if_tunnel_dead() -> bool:
    """CLI entry-point fallback: when CPU was not explicitly requested,
    probe the default backend in a killable subprocess and pin CPU if it
    is unresponsive (the dead-tunnel path), instead of hanging the caller
    on backend init. Returns True when it pinned."""
    if (os.environ.get("JAX_PLATFORMS") != "cpu"
            and probe_backend() == 0):
        pin_cpu_platform()
        return True
    return False

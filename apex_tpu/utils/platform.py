"""Platform pinning for CPU/virtual-mesh execution.

The image's axon sitecustomize registers the TPU-tunnel backend for every
interpreter; setting ``JAX_PLATFORMS=cpu`` in the environment does NOT stop
the hook from initializing (and possibly dialing) that backend — only the
``jax_platforms`` config flag does. Every CPU-bound entry point (tests,
virtual-mesh benchmarks, baseline generators) should call
:func:`pin_cpu_platform` before first device use instead of re-deriving
this recipe.
"""

from __future__ import annotations

import os


def probe_backend(timeout_s: int = 240) -> int:
    """Device count of the default backend, probed in a KILLABLE
    subprocess; 0 when init hangs or fails. The axon tunnel blocks forever
    inside backend init when its relay is down (observed in round 2) — a
    parent process's own first backend touch would hang with it, so this
    is the only safe way to ask. Healthy-platform cost: one extra backend
    dial in the child (~tens of seconds on a tunnel); a dead tunnel costs
    the full timeout once.

    When this process has ALREADY initialized its backends, asking jax
    directly is hang-safe and also sidesteps exclusive-device locks the
    child could trip over (e.g. the driver holding the TPU after
    ``entry()``) — do that instead of spawning.
    """
    import subprocess
    import sys

    try:
        from jax._src import xla_bridge

        if xla_bridge.backends_are_initialized():
            import jax

            return len(jax.devices())
    except (ImportError, AttributeError):
        pass  # fall through to the subprocess probe

    code = ("import jax, jax.numpy as jnp; "
            "x = jnp.ones((128, 128), jnp.bfloat16); "
            "assert float((x @ x).sum()) > 0; "
            "print(len(jax.devices()))")
    try:
        proc = subprocess.run([sys.executable, "-c", code],
                              capture_output=True, text=True,
                              timeout=timeout_s)
        if proc.returncode != 0:
            return 0
        return int(proc.stdout.strip().splitlines()[-1])
    except (subprocess.TimeoutExpired, ValueError, IndexError):
        return 0


def pin_cpu_platform(virtual_devices: int | None = None) -> None:
    """Force the CPU backend; optionally expose ``virtual_devices`` host
    devices (the multi-chip simulation used across the test suite).

    Call before the first jax backend use. Safe to call multiple times;
    an existing ``xla_force_host_platform_device_count`` flag is kept.
    """
    os.environ["JAX_PLATFORMS"] = "cpu"
    if virtual_devices is not None:
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + f" --xla_force_host_platform_device_count="
                f"{virtual_devices}").strip()
    import jax

    jax.config.update("jax_platforms", "cpu")

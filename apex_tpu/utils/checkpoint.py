"""Checkpoint save/load for train-state pytrees.

Reference context (SURVEY §5): model checkpointing is delegated to
``torch.save``; apex only contributes the amp/scaler state-dict entries
(``frontend.py:361-401``) and fp32 master saving
(``fp16_optimizer.py:209-270``). The TPU equivalents of those live on their
owning objects (``amp.state_dict``, ``FP16_Optimizer.state_dict``,
``LossScaler.state_dict``); this module supplies the ``torch.save`` role:
orbax when available (sharded-array aware, async-capable), numpy fallback.
"""

from __future__ import annotations

import os
import pickle
from typing import Any, Optional

import jax
import numpy as np

Pytree = Any


def save_checkpoint(path: str, state: Pytree, step: Optional[int] = None,
                    overwrite: bool = True) -> str:
    """Write ``state`` (any pytree of arrays + scalars) under ``path``.
    Returns the final checkpoint directory/file path."""
    try:
        import orbax.checkpoint as ocp

        p = os.path.abspath(path if step is None else f"{path}_{step}")
        ckptr = ocp.PyTreeCheckpointer()
        ckptr.save(p, jax.device_get(state), force=overwrite)
        return p
    except ImportError:
        p = (path if step is None else f"{path}_{step}") + ".npz.pkl"
        host = jax.tree_util.tree_map(np.asarray, jax.device_get(state))
        if not overwrite and os.path.exists(p):
            raise FileExistsError(p)
        with open(p, "wb") as f:
            pickle.dump(host, f)
        return p


def load_checkpoint(path: str, target: Optional[Pytree] = None) -> Pytree:
    """Read a checkpoint written by :func:`save_checkpoint`. ``target``:
    optional pytree of like-structured arrays used to restore dtypes/
    structure (orbax restore_args)."""
    try:
        import orbax.checkpoint as ocp

        if os.path.isdir(path):
            ckptr = ocp.PyTreeCheckpointer()
            restored = ckptr.restore(path)
            if target is not None:
                # scalar (non-array) target leaves — e.g. a scaler
                # state_dict's plain floats/ints — restore as-is
                restored = jax.tree_util.tree_map(
                    lambda t, r: (np.asarray(r, dtype=t.dtype)
                                  if hasattr(t, "dtype") else type(t)(r)),
                    target, restored)
            return restored
    except ImportError:
        pass
    with open(path, "rb") as f:
        return pickle.load(f)

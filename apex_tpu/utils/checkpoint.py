"""Checkpoint save/load for train-state pytrees.

Reference context (SURVEY §5): model checkpointing is delegated to
``torch.save``; apex only contributes the amp/scaler state-dict entries
(``frontend.py:361-401``) and fp32 master saving
(``fp16_optimizer.py:209-270``). The TPU equivalents of those live on their
owning objects (``amp.state_dict``, ``FP16_Optimizer.state_dict``,
``LossScaler.state_dict``); this module supplies the ``torch.save`` role:
orbax when available (sharded-array aware, async-capable), numpy fallback.

Durability contract (both backends): the fallback writes to a ``.tmp``
sibling and publishes with ``os.replace``, so a crash mid-save never leaves
a torn file under the final name, and a truncated/corrupt pickle on load is
reported as a clear error naming the path. The production layer above this
(atomic directories, manifests, checksums, retention, discovery) is
:class:`apex_tpu.resilience.CheckpointManager`.
"""

from __future__ import annotations

import os
import pickle
from typing import Any, Optional

import jax
import numpy as np

Pytree = Any

_PICKLE_SUFFIX = ".npz.pkl"


def _orbax():
    """The orbax.checkpoint module, or ``None`` (monkeypatchable seam —
    tests force the numpy/pickle fallback through it)."""
    try:
        import orbax.checkpoint as ocp

        return ocp
    except ImportError:
        return None


def save_checkpoint(path: str, state: Pytree, step: Optional[int] = None,
                    overwrite: bool = True) -> str:
    """Write ``state`` (any pytree of arrays + scalars) under ``path``.
    Returns the final checkpoint directory/file path. ``overwrite=False``
    refuses an existing destination BEFORE any device transfer or write."""
    ocp = _orbax()
    if ocp is not None:
        p = os.path.abspath(path if step is None else f"{path}_{step}")
        if not overwrite and os.path.exists(p):
            raise FileExistsError(p)
        ckptr = ocp.PyTreeCheckpointer()
        ckptr.save(p, jax.device_get(state), force=overwrite)
        return p
    p = (path if step is None else f"{path}_{step}") + _PICKLE_SUFFIX
    if not overwrite and os.path.exists(p):
        raise FileExistsError(p)
    host = jax.tree_util.tree_map(np.asarray, jax.device_get(state))
    # torn-write safety: stage then publish — a crash mid-dump leaves only
    # the .tmp sibling, never a truncated pickle under the final name
    import glob

    for stale in glob.glob(f"{glob.escape(p)}.tmp.*"):
        if not stale.endswith(f".{os.getpid()}"):
            try:  # a dead writer's orphan: don't leak one per crash
                os.remove(stale)
            except OSError:
                pass
    tmp = f"{p}.tmp.{os.getpid()}"
    try:
        with open(tmp, "wb") as f:
            pickle.dump(host, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, p)
    except BaseException:
        if os.path.exists(tmp):
            os.remove(tmp)
        raise
    return p


def load_checkpoint(path: str, target: Optional[Pytree] = None) -> Pytree:
    """Read a checkpoint written by :func:`save_checkpoint`. ``target``:
    optional pytree of like-structured arrays used to restore dtypes/
    structure (orbax restore_args)."""
    ocp = _orbax()
    if ocp is not None and os.path.isdir(path):
        ckptr = ocp.PyTreeCheckpointer()
        restored = ckptr.restore(path)
        if target is not None:
            # scalar (non-array) target leaves — e.g. a scaler
            # state_dict's plain floats/ints — restore as-is
            restored = jax.tree_util.tree_map(
                lambda t, r: (np.asarray(r, dtype=t.dtype)
                              if hasattr(t, "dtype") else type(t)(r)),
                target, restored)
        return restored
    try:
        with open(path, "rb") as f:
            return pickle.load(f)
    except (pickle.UnpicklingError, EOFError, AttributeError) as e:
        # a truncated tail raises EOFError, mid-file damage raises
        # UnpicklingError (or worse) — both mean the same thing to a caller
        raise ValueError(
            f"checkpoint '{path}' is truncated or corrupt and cannot be "
            f"unpickled ({type(e).__name__}: {e}); if an older checkpoint "
            "exists, resume from that instead") from e

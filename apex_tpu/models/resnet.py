"""NHWC ResNet for the imagenet example + DDP/SyncBN benchmarks.

Reference context: ``examples/imagenet/main_amp.py`` trains torchvision
ResNet-50 under amp O0-O3 + apex DDP (+ optional ``--sync_bn``); the
contrib ``bottleneck`` ext (``apex/contrib/csrc/bottleneck``) fuses the
conv-bn-relu bottleneck with cudnn-frontend. On TPU: NHWC is the native
layout, XLA fuses BN+ReLU into the convs on its own, and the bottleneck
block below IS the fused block (``apex_tpu.contrib.bottleneck`` re-exports
it). ``norm`` selects plain BatchNorm or the cross-device SyncBatchNorm.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp

from apex_tpu.parallel.sync_batchnorm import SyncBatchNorm


def make_norm(sync_bn: bool = False, axis_name: str = "dp",
              momentum: float = 0.1, eps: float = 1e-5):
    """Norm-layer factory: SyncBatchNorm across ``axis_name`` or local BN
    (ref ``--sync_bn`` flag, main_amp.py:150-160)."""
    if sync_bn:
        return functools.partial(SyncBatchNorm, momentum=momentum, eps=eps,
                                 axis_name=axis_name)
    return functools.partial(SyncBatchNorm, momentum=momentum, eps=eps,
                             axis_name=None)


class BottleneckBlock(nn.Module):
    """1x1 -> 3x3 -> 1x1 with identity/projection shortcut (the block the
    contrib ``fast_bottleneck`` ext fuses; ref ``bottleneck.py:112``)."""

    features: int
    strides: Tuple[int, int] = (1, 1)
    norm: Callable = SyncBatchNorm
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, use_running_average: bool = False):
        conv = functools.partial(nn.Conv, use_bias=False, dtype=self.dtype)
        bn = self.norm
        residual = x
        y = conv(self.features, (1, 1))(x)
        y = bn()(y, use_running_average)
        y = nn.relu(y)
        y = conv(self.features, (3, 3), self.strides)(y)
        y = bn()(y, use_running_average)
        y = nn.relu(y)
        y = conv(self.features * 4, (1, 1))(y)
        y = bn()(y, use_running_average)
        if residual.shape != y.shape:
            residual = conv(self.features * 4, (1, 1), self.strides,
                            name="proj_conv")(residual)
            residual = bn(name="proj_bn")(residual, use_running_average)
        return nn.relu(y + residual)


class BasicBlock(nn.Module):
    features: int
    strides: Tuple[int, int] = (1, 1)
    norm: Callable = SyncBatchNorm
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, use_running_average: bool = False):
        conv = functools.partial(nn.Conv, use_bias=False, dtype=self.dtype)
        residual = x
        y = conv(self.features, (3, 3), self.strides)(x)
        y = self.norm()(y, use_running_average)
        y = nn.relu(y)
        y = conv(self.features, (3, 3))(y)
        y = self.norm()(y, use_running_average)
        if residual.shape != y.shape:
            residual = conv(self.features, (1, 1), self.strides,
                            name="proj_conv")(residual)
            residual = self.norm(name="proj_bn")(residual,
                                                 use_running_average)
        return nn.relu(y + residual)


class ResNet(nn.Module):
    """NHWC ResNet (ref torchvision resnet50 as used by main_amp.py:88)."""

    stage_sizes: Sequence[int]
    block: Any = BottleneckBlock
    num_classes: int = 1000
    width: int = 64
    norm: Callable = SyncBatchNorm
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, use_running_average: bool = False):
        x = nn.Conv(self.width, (7, 7), (2, 2), use_bias=False,
                    dtype=self.dtype, name="conv_init")(x)
        x = self.norm(name="bn_init")(x, use_running_average)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), (2, 2), padding="SAME")
        for i, n_blocks in enumerate(self.stage_sizes):
            for j in range(n_blocks):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                x = self.block(self.width * 2 ** i, strides=strides,
                               norm=self.norm, dtype=self.dtype)(
                    x, use_running_average)
        x = jnp.mean(x, axis=(1, 2))
        x = x.astype(jnp.float32)
        return nn.Dense(self.num_classes, dtype=jnp.float32)(x)


ResNet50 = functools.partial(ResNet, stage_sizes=(3, 4, 6, 3),
                             block=BottleneckBlock)
ResNet18 = functools.partial(ResNet, stage_sizes=(2, 2, 2, 2),
                             block=BasicBlock)

"""Model zoo for the example trainers and benchmarks.

The reference ships no model library (its examples pull torchvision
ResNet-50 and a local DCGAN); here the equivalents live in-tree since there
is no torchvision on TPU: NHWC ResNet (ref ``examples/imagenet``) and DCGAN
generator/discriminator (ref ``examples/dcgan/main_amp.py``), plus the
Megatron GPT/BERT fixtures under ``apex_tpu.transformer.testing``.
"""

from apex_tpu.models.resnet import ResNet, ResNet18, ResNet50  # noqa: F401
from apex_tpu.models.dcgan import Discriminator, Generator  # noqa: F401

"""DCGAN generator/discriminator (ref ``examples/dcgan/main_amp.py``).

The reference example exercises amp with TWO models and THREE losses
(``main_amp.py:214-253`` — errD_real, errD_fake, errG each with its own
``loss_id``); these are the minimal NHWC equivalents of its netG/netD."""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax.numpy as jnp


class Generator(nn.Module):
    """z (B, 1, 1, nz) -> image (B, isize, isize, nc)."""

    isize: int = 64
    nz: int = 100
    ngf: int = 64
    nc: int = 3
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, z, train: bool = True):
        x = z
        # 1x1 -> 4x4
        mult = self.isize // 8
        x = nn.ConvTranspose(self.ngf * mult, (4, 4), (1, 1), padding="VALID",
                             use_bias=False, dtype=self.dtype)(x)
        x = nn.BatchNorm(use_running_average=not train)(x)
        x = nn.relu(x)
        size = 4
        while size < self.isize // 2:
            mult //= 2
            x = nn.ConvTranspose(self.ngf * mult, (4, 4), (2, 2),
                                 padding="SAME", use_bias=False,
                                 dtype=self.dtype)(x)
            x = nn.BatchNorm(use_running_average=not train)(x)
            x = nn.relu(x)
            size *= 2
        x = nn.ConvTranspose(self.nc, (4, 4), (2, 2), padding="SAME",
                             use_bias=False, dtype=self.dtype)(x)
        return jnp.tanh(x)


class Discriminator(nn.Module):
    """image (B, isize, isize, nc) -> logit (B,)."""

    isize: int = 64
    ndf: int = 64
    nc: int = 3
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = nn.Conv(self.ndf, (4, 4), (2, 2), padding="SAME", use_bias=False,
                    dtype=self.dtype)(x)
        x = nn.leaky_relu(x, 0.2)
        size = self.isize // 2
        mult = 1
        while size > 4:
            mult *= 2
            x = nn.Conv(self.ndf * mult, (4, 4), (2, 2), padding="SAME",
                        use_bias=False, dtype=self.dtype)(x)
            x = nn.BatchNorm(use_running_average=not train)(x)
            x = nn.leaky_relu(x, 0.2)
            size //= 2
        x = nn.Conv(1, (4, 4), (1, 1), padding="VALID", use_bias=False,
                    dtype=self.dtype)(x)
        return x.reshape(x.shape[0])

"""Fused-gate RNN stack (ref ``apex/RNN``, deprecated upstream).

Reference: ``RNN/RNNBackend.py:25-300`` + ``cells.py`` + ``models.py`` —
pure-PyTorch RNN/LSTM/GRU/mLSTM with fused gate math, stacked and
bidirectional wrappers. Kept for capability parity; on TPU the gate GEMMs
hit the MXU and ``lax.scan`` carries the recurrence (one compiled step body
for any sequence length).
"""

from apex_tpu.RNN.models import GRU, LSTM, RNNReLU, RNNTanh, mLSTM  # noqa: F401

__all__ = ["LSTM", "GRU", "RNNReLU", "RNNTanh", "mLSTM"]

"""RNN cells + stacked/bidirectional drivers.

Reference mapping: cell math mirrors ``apex/RNN/cells.py`` (fused LSTM gate
block, mLSTM multiplicative integration) and ``RNNBackend.py`` ``RNNCell``
(:223, gate_multiplier pattern); the stacking/bidirectional drivers mirror
``bidirectionalRNN``/``stackedRNN`` (:25,69). Layout: (batch, time, features).
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax


class _Cell(nn.Module):
    """One recurrent layer scanned over time. ``gates``: multiplier on the
    hidden size for the fused gate GEMM (ref gate_multiplier)."""

    hidden_size: int
    gates: int
    step_fn: Callable  # (input_gates, hidden_gates, carry) -> (carry, out)
    carry_size: int = 1  # number of state tensors (h; or h,c)
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, init_carry=None):
        b = x.shape[0]
        g = self.gates * self.hidden_size
        w_i = self.param("w_ih", nn.initializers.lecun_normal(),
                         (x.shape[-1], g), self.dtype)
        w_h = self.param("w_hh", nn.initializers.lecun_normal(),
                         (self.hidden_size, g), self.dtype)
        bias = self.param("bias", nn.initializers.zeros, (g,), self.dtype)
        if init_carry is None:
            init_carry = tuple(
                jnp.zeros((b, self.hidden_size), self.dtype)
                for _ in range(self.carry_size))

        # fused input GEMM over the whole sequence (one MXU matmul)
        xg = jnp.einsum("bti,ig->btg", x, w_i) + bias

        def step(carry, xg_t):
            h = carry[0]
            # input and hidden gate contributions kept separate: GRU's
            # candidate gate applies the reset gate to the hidden part only
            return self.step_fn(xg_t, h @ w_h, carry)

        carry, ys = lax.scan(step, init_carry, xg.swapaxes(0, 1))
        return ys.swapaxes(0, 1), carry


def _lstm_step(xg, hg, carry):
    h, c = carry
    i, f, g, o = jnp.split(xg + hg, 4, axis=-1)
    i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
    c_new = f * c + i * jnp.tanh(g)
    h_new = o * jnp.tanh(c_new)
    return (h_new, c_new), h_new


def _gru_step(xg, hg, carry):
    # torch.nn.GRUCell semantics (the reference re-exports torch's GRU):
    # r gates only the hidden-path term of the candidate. The single fused
    # bias lives on the input path (b = b_ih + b_hh for r/z; b_hn ≡ 0).
    (h,) = carry
    xr, xz, xn = jnp.split(xg, 3, axis=-1)
    hr, hz, hn = jnp.split(hg, 3, axis=-1)
    r, z = jax.nn.sigmoid(xr + hr), jax.nn.sigmoid(xz + hz)
    n = jnp.tanh(xn + r * hn)
    h_new = (1 - z) * n + z * h
    return (h_new,), h_new


def _rnn_step(act):
    def step(xg, hg, carry):
        h_new = act(xg + hg)
        return (h_new,), h_new

    return step


class _Stacked(nn.Module):
    """stackedRNN + bidirectionalRNN driver (ref RNNBackend.py:25-120)."""

    hidden_size: int
    num_layers: int
    gates: int
    step_fn: Callable
    carry_size: int
    bidirectional: bool = False
    dropout: float = 0.0
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, deterministic: bool = True):
        h = x
        for layer in range(self.num_layers):
            fwd, _ = _Cell(self.hidden_size, self.gates, self.step_fn,
                           self.carry_size, self.dtype,
                           name=f"layer_{layer}")(h)
            if self.bidirectional:
                bwd, _ = _Cell(self.hidden_size, self.gates, self.step_fn,
                               self.carry_size, self.dtype,
                               name=f"layer_{layer}_rev")(h[:, ::-1])
                h = jnp.concatenate([fwd, bwd[:, ::-1]], axis=-1)
            else:
                h = fwd
            if self.dropout > 0 and not deterministic \
                    and layer < self.num_layers - 1:
                h = nn.Dropout(self.dropout, deterministic=False)(h)
        return h


def LSTM(input_size, hidden_size, num_layers=1, bidirectional=False,
         dropout=0.0, dtype=jnp.float32):
    """Ref ``models.py`` LSTM factory."""
    del input_size  # inferred at first call (flax lazy init)
    return _Stacked(hidden_size, num_layers, 4, _lstm_step, 2,
                    bidirectional, dropout, dtype)


def GRU(input_size, hidden_size, num_layers=1, bidirectional=False,
        dropout=0.0, dtype=jnp.float32):
    del input_size
    return _Stacked(hidden_size, num_layers, 3, _gru_step, 1,
                    bidirectional, dropout, dtype)


def RNNTanh(input_size, hidden_size, num_layers=1, bidirectional=False,
            dropout=0.0, dtype=jnp.float32):
    del input_size
    return _Stacked(hidden_size, num_layers, 1, _rnn_step(jnp.tanh), 1,
                    bidirectional, dropout, dtype)


def RNNReLU(input_size, hidden_size, num_layers=1, bidirectional=False,
            dropout=0.0, dtype=jnp.float32):
    del input_size
    return _Stacked(hidden_size, num_layers, 1, _rnn_step(jax.nn.relu), 1,
                    bidirectional, dropout, dtype)


class _MLSTMCell(nn.Module):
    """Multiplicative LSTM (ref ``cells.py`` mLSTM: m = (W_mx x) * (W_mh h)
    modulates the hidden input to the gate block)."""

    hidden_size: int
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, init_carry=None):
        b = x.shape[0]
        hs = self.hidden_size
        w_i = self.param("w_ih", nn.initializers.lecun_normal(),
                         (x.shape[-1], 4 * hs), self.dtype)
        w_h = self.param("w_hh", nn.initializers.lecun_normal(),
                         (hs, 4 * hs), self.dtype)
        w_mx = self.param("w_mx", nn.initializers.lecun_normal(),
                          (x.shape[-1], hs), self.dtype)
        w_mh = self.param("w_mh", nn.initializers.lecun_normal(),
                          (hs, hs), self.dtype)
        bias = self.param("bias", nn.initializers.zeros, (4 * hs,),
                          self.dtype)
        if init_carry is None:
            init_carry = (jnp.zeros((b, hs), self.dtype),
                          jnp.zeros((b, hs), self.dtype))
        xg = jnp.einsum("bti,ig->btg", x, w_i) + bias
        xm = jnp.einsum("bti,ih->bth", x, w_mx)

        def step(carry, inp):
            xg_t, xm_t = inp
            h, c = carry
            m = xm_t * (h @ w_mh)
            return _lstm_step(xg_t, m @ w_h, (h, c))

        carry, ys = lax.scan(step, init_carry,
                             (xg.swapaxes(0, 1), xm.swapaxes(0, 1)))
        return ys.swapaxes(0, 1), carry


def mLSTM(input_size, hidden_size, dtype=jnp.float32):
    del input_size
    return _MLSTMCell(hidden_size, dtype)

"""FusedAdam — Adam/AdamW with the reference's exact update math.

Reference: ``apex/optimizers/fused_adam.py:4-165`` (python driver grouping
params by dtype and launching ``multi_tensor_adam``) and the kernel math in
``csrc/multi_tensor_adam.cu:24-140``:

ADAM_MODE_0 (adamw / decoupled decay)::

    m = b1*m + (1-b1)*g
    v = b2*v + (1-b2)*g*g
    mhat = m / (1 - b1^t)        (when bias_correction)
    vhat = v / (1 - b2^t)
    p  -= lr * (mhat / (sqrt(vhat) + eps) + weight_decay * p)

ADAM_MODE_1 (classic adam / L2 regularization)::

    g  += weight_decay * p       (before the moments)
    ... same moment update, no decay term in the step

On TPU the whole pytree update is one jitted program — the equivalent of the
single chunked CUDA launch. State (m, v, step) is an explicit pytree and is
kept in fp32 regardless of param dtype (the kernel stores fp32 moments too).
"""

from __future__ import annotations

from typing import Any, NamedTuple, Tuple

import jax
import jax.numpy as jnp
import optax

from apex_tpu.optimizers._common import Schedule, tree_map, value_at


class FusedAdamState(NamedTuple):
    count: jnp.ndarray  # i32 step counter ("step" in the reference state)
    mu: Any  # first moments, fp32
    nu: Any  # second moments, fp32


def FusedAdam(
    lr: Schedule = 1e-3,
    bias_correction: bool = True,
    betas: Tuple[float, float] = (0.9, 0.999),
    eps: float = 1e-8,
    adam_w_mode: bool = True,
    weight_decay: float = 0.0,
    amsgrad: bool = False,
    capturable: bool = True,  # always "capturable": everything lives on device
    fused_tail: str = "auto",
) -> optax.GradientTransformation:
    """Build the transform (ref ``fused_adam.py:4`` constructor signature;
    ``step`` at ``:92``). ``amsgrad`` is unsupported, as in the reference
    (``fused_adam.py:77-78`` raises).

    ``fused_tail``: run the per-leaf update tail as ONE Pallas kernel
    (``ops/fused_update.py`` — the actual "fused" of the reference's
    multi_tensor launch, rebuilt for Mosaic) — "auto" on compiled TPU
    backends, "on" forces (interpret off-TPU), "off" keeps the XLA op
    chain."""
    if amsgrad:
        raise RuntimeError("FusedAdam does not support the AMSGrad variant.")
    from apex_tpu.ops.fused_update import resolve_fused

    resolve_fused(fused_tail, what="fused_tail")  # validate eagerly
    b1, b2 = betas

    def init(params):
        zeros = lambda p: jnp.zeros(p.shape, jnp.float32)
        return FusedAdamState(
            count=jnp.zeros((), jnp.int32),
            mu=tree_map(zeros, params),
            nu=tree_map(zeros, params),
        )

    def update(grads, state, params):
        if params is None:
            raise ValueError("FusedAdam requires params in update()")
        count = state.count + 1
        step_lr = value_at(lr, count)
        t = count.astype(jnp.float32)
        # bias corrections computed once per step, scalar (ref fused_adam.py:106-112)
        c1 = 1.0 - jnp.power(b1, t) if bias_correction else jnp.asarray(1.0)
        c2 = 1.0 - jnp.power(b2, t) if bias_correction else jnp.asarray(1.0)

        from apex_tpu.ops.fused_update import fused_adam_tail, resolve_fused

        use_fused = resolve_fused(fused_tail, what="fused_tail")

        def leaf(g, p, m, v):
            g = g.astype(jnp.float32)
            p32 = p.astype(jnp.float32)
            if use_fused:
                # the whole tail as ONE kernel per leaf — the Mosaic
                # analogue of the reference's chunked multi_tensor_adam
                upd, m_new, v_new = fused_adam_tail(
                    g, m, v, p32, c1, c2, betas=betas, eps=eps,
                    weight_decay=weight_decay, adam_w_mode=adam_w_mode,
                    use_pallas=True)
                return (-step_lr * upd).astype(p.dtype), m_new, v_new
            if not adam_w_mode and weight_decay != 0.0:
                g = g + weight_decay * p32  # ADAM_MODE_1 (multi_tensor_adam.cu:60)
            m_new = b1 * m + (1.0 - b1) * g
            v_new = b2 * v + (1.0 - b2) * g * g
            mhat = m_new / c1
            vhat = v_new / c2
            upd = mhat / (jnp.sqrt(vhat) + eps)
            if adam_w_mode and weight_decay != 0.0:
                upd = upd + weight_decay * p32  # ADAM_MODE_0 decoupled decay
            return (-step_lr * upd).astype(p.dtype), m_new, v_new

        flat = tree_map(leaf, grads, params, state.mu, state.nu)
        updates = tree_map(lambda t3: t3[0], flat, is_leaf=lambda x: isinstance(x, tuple))
        mu = tree_map(lambda t3: t3[1], flat, is_leaf=lambda x: isinstance(x, tuple))
        nu = tree_map(lambda t3: t3[2], flat, is_leaf=lambda x: isinstance(x, tuple))
        return updates, FusedAdamState(count, mu, nu)

    return optax.GradientTransformation(init, update)

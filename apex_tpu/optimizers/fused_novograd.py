"""FusedNovoGrad — layer-wise normalized gradient descent with momentum.

Reference: ``apex/optimizers/fused_novograd.py:4-135`` and
``csrc/multi_tensor_novograd.cu``; the second moment is **per tensor**, not
per element (the reference keeps a flat ``exp_avg_sq`` vector, one scalar per
tensor, ``fused_novograd.py:95-100``):

    norm = ||g||_2^2        (norm_type=2; norm_type=0 -> max|g|)
    v    = norm                       on the first step (init_zero=False)
         = b2*v + (1-b2)*norm         afterwards
    d    = g / (sqrt(v) + eps) + weight_decay * p
    m    = b1*m + beta3*d             (beta3 = 1-b1 when grad_averaging)
    p   -= lr * m
"""

from __future__ import annotations

from typing import Any, NamedTuple, Tuple

import jax.numpy as jnp
import optax

from apex_tpu.optimizers._common import Schedule, tree_map, value_at


class FusedNovoGradState(NamedTuple):
    count: jnp.ndarray
    mu: Any  # per-element momentum
    nu: Any  # per-TENSOR second moment (scalar per leaf)


def FusedNovoGrad(
    lr: Schedule = 1e-3,
    bias_correction: bool = True,
    betas: Tuple[float, float] = (0.95, 0.98),
    eps: float = 1e-8,
    weight_decay: float = 0.0,
    amsgrad: bool = False,
    reg_inside_moment: bool = False,
    grad_averaging: bool = True,
    norm_type: int = 2,
    init_zero: bool = False,
) -> optax.GradientTransformation:
    if amsgrad:
        raise RuntimeError("FusedNovoGrad does not support the AMSGrad variant.")
    if norm_type not in (0, 2):
        raise ValueError("norm_type must be 2 (L2) or 0 (inf)")
    b1, b2 = betas
    beta3 = (1.0 - b1) if grad_averaging else 1.0

    def init(params):
        return FusedNovoGradState(
            count=jnp.zeros((), jnp.int32),
            mu=tree_map(lambda p: jnp.zeros(p.shape, jnp.float32), params),
            nu=tree_map(lambda p: jnp.zeros((), jnp.float32), params),
        )

    def update(grads, state, params):
        if params is None:
            raise ValueError("FusedNovoGrad requires params in update()")
        count = state.count + 1
        step_lr = value_at(lr, count)
        first = state.count == 0

        def leaf(g, p, m, v):
            g = g.astype(jnp.float32)
            p32 = p.astype(jnp.float32)
            if norm_type == 2:
                norm = jnp.sum(g * g)
            else:
                norm = jnp.max(jnp.abs(g)) ** 2
            if init_zero:
                v_new = b2 * v + (1.0 - b2) * norm
            else:
                v_new = jnp.where(first, norm, b2 * v + (1.0 - b2) * norm)
            denom = jnp.sqrt(v_new) + eps
            d = g / denom
            # reg_inside_moment=True folds decay into the momentum input;
            # False (default) decouples it from the momentum ("MD" decay,
            # ref fused_novograd.py:28-33 + multi_tensor_novograd.cu moment
            # mode).
            if weight_decay != 0.0 and reg_inside_moment:
                d = d + weight_decay * p32
            m_new = b1 * m + beta3 * d
            step = m_new
            if weight_decay != 0.0 and not reg_inside_moment:
                step = step + weight_decay * p32
            return (-step_lr * step).astype(p.dtype), m_new, v_new

        flat = tree_map(leaf, grads, params, state.mu, state.nu)
        is_t = lambda x: isinstance(x, tuple)
        updates = tree_map(lambda t3: t3[0], flat, is_leaf=is_t)
        mu = tree_map(lambda t3: t3[1], flat, is_leaf=is_t)
        nu = tree_map(lambda t3: t3[2], flat, is_leaf=is_t)
        return updates, FusedNovoGradState(count, mu, nu)

    return optax.GradientTransformation(init, update)

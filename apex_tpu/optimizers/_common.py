"""Shared plumbing for the fused optimizer suite.

The reference's optimizers exist because eager PyTorch launches one kernel per
tensor per op; ``multi_tensor_applier`` batches the whole param list into a few
chunked kernels (ref ``apex/multi_tensor_apply/multi_tensor_apply.py:3-30``,
``csrc/multi_tensor_apply.cuh:16-70``). Under XLA a jitted update over the
param pytree compiles to the same handful of fused loops, so the TPU-native
design is: **optimizer = optax-style pure transform over pytrees**; the
"fused" quality comes from jit, not a special kernel. Each optimizer below
reproduces the reference's update *math* exactly (cited per file) and follows
the optax ``GradientTransformation`` protocol so it composes with the JAX
ecosystem.
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple, Optional, Union

import jax
import jax.numpy as jnp
import optax

Schedule = Union[float, Callable[[jnp.ndarray], jnp.ndarray]]


def value_at(lr: Schedule, count: jnp.ndarray) -> jnp.ndarray:
    return lr(count) if callable(lr) else jnp.asarray(lr, jnp.float32)


def tree_map(f, *trees, is_leaf=None):
    return jax.tree_util.tree_map(f, *trees, is_leaf=is_leaf)


def global_norm(tree) -> jnp.ndarray:
    """L2 norm over the whole pytree (ref ``amp_C.multi_tensor_l2norm``
    per-tensor + reduction, ``csrc/multi_tensor_l2norm_kernel.cu``)."""
    leaves = jax.tree_util.tree_leaves(tree)
    if not leaves:
        return jnp.asarray(0.0, jnp.float32)
    return jnp.sqrt(
        sum(jnp.sum(jnp.square(g.astype(jnp.float32))) for g in leaves)
    )


def apply_updates(params, updates):
    """params + updates, preserving each param's dtype (masters stay fp32)."""
    return tree_map(
        lambda p, u: (p.astype(jnp.float32) + u.astype(jnp.float32)).astype(p.dtype),
        params,
        updates,
    )


class ScaleByStep(NamedTuple):
    count: jnp.ndarray


def chain(*transforms) -> optax.GradientTransformation:
    return optax.chain(*transforms)

"""FusedLAMB — layer-wise adaptive moments with global grad-norm clipping.

Reference: ``apex/optimizers/fused_lamb.py:4-214`` (driver computing per-tensor
L2 norms via ``multi_tensor_l2norm`` at ``:124-133``, then the two-stage
``multi_tensor_lamb``) and ``csrc/multi_tensor_lamb.cu:41``:

stage 1 (per element)::

    clip = max_grad_norm > 0 and global_grad_norm > max_grad_norm
           ? global_grad_norm / max_grad_norm : 1
    g' = g / clip
    m = b1*m + beta3*g'            (beta3 = 1-b1 when grad_averaging else 1)
    v = b2*v + (1-b2)*g'*g'
    update = (m/c1) / (sqrt(v/c2) + eps) + weight_decay * p

stage 2 (per tensor)::

    w_norm = ||p||,  u_norm = ||update||
    ratio  = (w_norm > 0 and u_norm > 0) ? w_norm / u_norm : 1
    applied only when weight_decay != 0, unless use_nvlamb
    p -= lr * ratio * update
"""

from __future__ import annotations

from typing import Any, NamedTuple, Tuple

import jax
import jax.numpy as jnp
import optax

from apex_tpu.optimizers._common import Schedule, global_norm, tree_map, value_at


class FusedLAMBState(NamedTuple):
    count: jnp.ndarray
    mu: Any
    nu: Any


def FusedLAMB(
    lr: Schedule = 1e-3,
    bias_correction: bool = True,
    betas: Tuple[float, float] = (0.9, 0.999),
    eps: float = 1e-6,
    weight_decay: float = 0.01,
    amsgrad: bool = False,
    adam_w_mode: bool = True,
    grad_averaging: bool = True,
    max_grad_norm: float = 1.0,
    use_nvlamb: bool = False,
) -> optax.GradientTransformation:
    if amsgrad:
        raise RuntimeError("FusedLAMB does not support the AMSGrad variant.")
    if not adam_w_mode:
        raise RuntimeError(
            "FusedLAMB only supports the decoupled (adamw) decay mode, "
            "as in the reference kernel."
        )
    b1, b2 = betas
    beta3 = (1.0 - b1) if grad_averaging else 1.0

    def init(params):
        zeros = lambda p: jnp.zeros(p.shape, jnp.float32)
        return FusedLAMBState(
            count=jnp.zeros((), jnp.int32),
            mu=tree_map(zeros, params),
            nu=tree_map(zeros, params),
        )

    def update(grads, state, params):
        if params is None:
            raise ValueError("FusedLAMB requires params in update()")
        count = state.count + 1
        step_lr = value_at(lr, count)
        t = count.astype(jnp.float32)
        c1 = 1.0 - jnp.power(b1, t) if bias_correction else jnp.asarray(1.0)
        c2 = 1.0 - jnp.power(b2, t) if bias_correction else jnp.asarray(1.0)

        # global grad norm over every param (ref fused_lamb.py:124-133)
        gnorm = global_norm(grads)
        if max_grad_norm > 0:
            clip = jnp.where(gnorm > max_grad_norm, gnorm / max_grad_norm, 1.0)
        else:
            clip = jnp.asarray(1.0)

        def leaf(g, p, m, v):
            g = g.astype(jnp.float32) / clip
            p32 = p.astype(jnp.float32)
            m_new = b1 * m + beta3 * g
            v_new = b2 * v + (1.0 - b2) * g * g
            upd = (m_new / c1) / (jnp.sqrt(v_new / c2) + eps)
            if weight_decay != 0.0:
                upd = upd + weight_decay * p32
            w_norm = jnp.sqrt(jnp.sum(p32 * p32))
            u_norm = jnp.sqrt(jnp.sum(upd * upd))
            ratio = jnp.where(
                (w_norm > 0) & (u_norm > 0), w_norm / u_norm, 1.0
            )
            if weight_decay == 0.0 and not use_nvlamb:
                ratio = jnp.asarray(1.0)
            return (-step_lr * ratio * upd).astype(p.dtype), m_new, v_new

        flat = tree_map(leaf, grads, params, state.mu, state.nu)
        is_t = lambda x: isinstance(x, tuple)
        updates = tree_map(lambda t3: t3[0], flat, is_leaf=is_t)
        mu = tree_map(lambda t3: t3[1], flat, is_leaf=is_t)
        nu = tree_map(lambda t3: t3[2], flat, is_leaf=is_t)
        return updates, FusedLAMBState(count, mu, nu)

    return optax.GradientTransformation(init, update)


def FusedMixedPrecisionLamb(
    lr: Schedule = 1e-3,
    step: int = 0,
    bias_correction: bool = True,
    betas: Tuple[float, float] = (0.9, 0.999),
    eps: float = 1e-6,
    weight_decay: float = 0.01,
    amsgrad: bool = False,
    grad_averaging: bool = True,
    max_grad_norm: float = 1.0,
    use_nvlamb: bool = False,
    reduced_precision_dtype=None,
) -> optax.GradientTransformation:
    """Mixed-precision LAMB (ref ``apex/optimizers/fused_mixed_precision_lamb.py:8``,
    step ``:140``): fp32 master params/state with bf16/fp16 model params and a
    ``grad_scaler`` argument.

    In the functional design the fp32 masters + cast-on-forward live in
    :mod:`apex_tpu.amp` (``initialize``/``model_params``/``apply_grads``), so
    this is LAMB with an unscale hook: pass ``grad_scale`` (the current loss
    scale) via ``optax``'s extra-args convention by wrapping grads before
    ``update`` — or simply use :func:`apex_tpu.amp.apply_grads` with this
    transform, which is the supported path. ``reduced_precision_dtype`` is
    accepted for signature parity; dtype handling is the amp layer's job.
    """
    del step, reduced_precision_dtype
    return FusedLAMB(
        lr=lr,
        bias_correction=bias_correction,
        betas=betas,
        eps=eps,
        weight_decay=weight_decay,
        amsgrad=amsgrad,
        grad_averaging=grad_averaging,
        max_grad_norm=max_grad_norm,
        use_nvlamb=use_nvlamb,
    )

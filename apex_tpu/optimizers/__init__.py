"""Fused optimizer suite (L3) — ref ``apex/optimizers/__init__.py``.

Each is an optax-style ``GradientTransformation`` factory reproducing the
reference kernel's update math exactly; "fused" on TPU means the whole pytree
update compiles to a handful of XLA loops under jit (the capability the
reference needs ``multi_tensor_applier`` + chunked CUDA kernels for).
"""

from apex_tpu.optimizers.fused_adam import FusedAdam, FusedAdamState  # noqa: F401
from apex_tpu.optimizers.fused_adagrad import (  # noqa: F401
    FusedAdagrad,
    FusedAdagradState,
)
from apex_tpu.optimizers.fused_lamb import (  # noqa: F401
    FusedLAMB,
    FusedLAMBState,
    FusedMixedPrecisionLamb,
)
from apex_tpu.optimizers.fused_novograd import (  # noqa: F401
    FusedNovoGrad,
    FusedNovoGradState,
)
from apex_tpu.optimizers.fused_sgd import FusedSGD, FusedSGDState  # noqa: F401
from apex_tpu.optimizers._common import apply_updates, global_norm  # noqa: F401
from apex_tpu.optimizers.grad_accumulation import (  # noqa: F401
    accumulate_gradients,
    accumulate_into_main_grads,
    init_main_grads,
)
from apex_tpu.parallel.larc import LARC, larc_transform  # noqa: F401

__all__ = [
    "accumulate_gradients",
    "accumulate_into_main_grads",
    "init_main_grads",
    "FusedAdam",
    "FusedAdagrad",
    "FusedLAMB",
    "FusedMixedPrecisionLamb",
    "FusedNovoGrad",
    "FusedSGD",
    "LARC",
    "apply_updates",
    "global_norm",
    "larc_transform",
]

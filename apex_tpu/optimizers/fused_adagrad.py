"""FusedAdagrad.

Reference: ``apex/optimizers/fused_adagrad.py:5-122`` and
``csrc/multi_tensor_adagrad.cu``:

MODE_0 (L2, default)::

    g += weight_decay * p
    h += g*g
    p -= lr * g / (sqrt(h) + eps)

MODE_1 (adagrad_w, decoupled)::

    h += g*g
    p -= lr * (g / (sqrt(h) + eps) + weight_decay * p)
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax.numpy as jnp
import optax

from apex_tpu.optimizers._common import Schedule, tree_map, value_at


class FusedAdagradState(NamedTuple):
    count: jnp.ndarray
    sum: Any  # accumulated squared grads ("sum" in torch/apex state)


def FusedAdagrad(
    lr: Schedule = 1e-2,
    eps: float = 1e-10,
    weight_decay: float = 0.0,
    adagrad_w_mode: bool = False,
) -> optax.GradientTransformation:
    def init(params):
        return FusedAdagradState(
            count=jnp.zeros((), jnp.int32),
            sum=tree_map(lambda p: jnp.zeros(p.shape, jnp.float32), params),
        )

    def update(grads, state, params):
        if params is None:
            raise ValueError("FusedAdagrad requires params in update()")
        count = state.count + 1
        step_lr = value_at(lr, count)

        def leaf(g, p, h):
            g = g.astype(jnp.float32)
            p32 = p.astype(jnp.float32)
            if not adagrad_w_mode and weight_decay != 0.0:
                g = g + weight_decay * p32
            h_new = h + g * g
            upd = g / (jnp.sqrt(h_new) + eps)
            if adagrad_w_mode and weight_decay != 0.0:
                upd = upd + weight_decay * p32
            return (-step_lr * upd).astype(p.dtype), h_new

        flat = tree_map(leaf, grads, params, state.sum)
        is_t = lambda x: isinstance(x, tuple)
        updates = tree_map(lambda t: t[0], flat, is_leaf=is_t)
        sums = tree_map(lambda t: t[1], flat, is_leaf=is_t)
        return updates, FusedAdagradState(count, sums)

    return optax.GradientTransformation(init, update)

"""fp32 main-grad accumulation across microbatches.

Reference capability: ``csrc/megatron/fused_weight_gradient_dense.cpp`` +
``apex/transformer/tensor_parallel/layers.py:217-320`` — each backward GEMM
accumulates dW directly into a persistent fp32 ``main_grad`` buffer, so a
half-precision model never sums half-precision gradients across microbatches
(bf16/fp16 addition loses low bits once grads differ in magnitude).

TPU re-design: gradients come out of ``jax.grad`` as a pytree per
microbatch, so "fuse the accumulation into the GEMM" becomes "cast+add the
microbatch grads into an fp32 accumulator inside the jitted step" — XLA
fuses the cast+add into the dW GEMM epilogue (it consumes the GEMM result
directly; nothing round-trips through a half-precision buffer). The loop
over microbatches is a ``lax.scan``, keeping one copy of the fp32
accumulator live regardless of microbatch count.
"""

from __future__ import annotations

from typing import Any, Callable, Tuple

import jax
import jax.numpy as jnp
from jax import lax

Pytree = Any


def init_main_grads(params: Pytree) -> Pytree:
    """fp32 zero accumulators shaped like ``params`` (ref ``main_grad``
    buffers allocated at DDP/optimizer setup)."""
    return jax.tree_util.tree_map(
        lambda p: jnp.zeros(p.shape, jnp.float32), params)


def accumulate_into_main_grads(main_grads: Pytree, grads: Pytree) -> Pytree:
    """``main += fp32(grad)`` leaf-wise — the fused accumulation step."""
    return jax.tree_util.tree_map(
        lambda m, g: m + g.astype(jnp.float32), main_grads, grads)


def accumulate_gradients(
    loss_fn: Callable[..., jnp.ndarray],
    params: Pytree,
    microbatches: Pytree,
    mean: bool = True,
) -> Tuple[jnp.ndarray, Pytree]:
    """Run ``loss_fn(params, microbatch)`` over stacked microbatches,
    accumulating gradients in fp32.

    ``microbatches``: pytree whose leaves have a leading microbatch axis
    (shape ``(n_micro, ...)``). Returns ``(loss, main_grads)`` — the summed
    (or with ``mean``, averaged) loss and fp32 gradient pytree. Model dtype
    is untouched: each microbatch's backward produces model-dtype grads that
    are cast+added into the fp32 accumulator (ref gradient_accumulation_fusion
    semantics), never summed in half precision.
    """
    n_micro = jax.tree_util.tree_leaves(microbatches)[0].shape[0]
    grad_fn = jax.value_and_grad(loss_fn)

    # Seed the accumulator from microbatch 0 rather than zeros: under
    # shard_map a zero init would be mesh-invariant while the grads vary
    # over the data axes, which scan rejects; deriving the init from a real
    # backward gives it the right variance automatically.
    mb0 = jax.tree_util.tree_map(lambda x: x[0], microbatches)
    loss0, grads0 = grad_fn(params, mb0)
    init = (loss0.astype(jnp.float32),
            jax.tree_util.tree_map(lambda g: g.astype(jnp.float32), grads0))

    def step(carry, mb):
        loss_acc, main = carry
        loss, grads = grad_fn(params, mb)
        main = accumulate_into_main_grads(main, grads)
        return (loss_acc + loss.astype(jnp.float32), main), None

    if n_micro > 1:
        rest = jax.tree_util.tree_map(lambda x: x[1:], microbatches)
        (loss, main_grads), _ = lax.scan(step, init, rest)
    else:
        loss, main_grads = init
    if mean:
        inv = 1.0 / n_micro
        loss = loss * inv
        main_grads = jax.tree_util.tree_map(lambda g: g * inv, main_grads)
    return loss, main_grads

"""FusedSGD — SGD + momentum/dampening/nesterov with the reference math.

Reference: ``apex/optimizers/fused_sgd.py:6-213`` (driver) and
``csrc/multi_tensor_sgd_kernel.cu:30-140``:

    d = g + weight_decay * p                  (wd before momentum, default)
    buf = momentum * buf + (1 - dampening) * d     (first step: buf = d)
    step = d + momentum * buf   if nesterov else buf
    p -= lr * step

``wd_after_momentum=True`` instead applies decay to the momentum-combined
update (ref ``fused_sgd.py:46-52``, kernel ``wd_after_momentum`` branch).
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax.numpy as jnp
import optax

from apex_tpu.optimizers._common import Schedule, tree_map, value_at


class FusedSGDState(NamedTuple):
    count: jnp.ndarray
    momentum_buffer: Any


def FusedSGD(
    lr: Schedule = 1e-3,
    momentum: float = 0.0,
    dampening: float = 0.0,
    weight_decay: float = 0.0,
    nesterov: bool = False,
    wd_after_momentum: bool = False,
) -> optax.GradientTransformation:
    if nesterov and (momentum <= 0 or dampening != 0):
        raise ValueError("Nesterov momentum requires a momentum and zero dampening")

    def init(params):
        return FusedSGDState(
            count=jnp.zeros((), jnp.int32),
            momentum_buffer=tree_map(
                lambda p: jnp.zeros(p.shape, jnp.float32), params
            ),
        )

    def update(grads, state, params):
        if params is None:
            raise ValueError("FusedSGD requires params in update()")
        count = state.count + 1
        step_lr = value_at(lr, count)
        first = state.count == 0

        def leaf(g, p, buf):
            g = g.astype(jnp.float32)
            p32 = p.astype(jnp.float32)
            d = g if wd_after_momentum else g + weight_decay * p32
            if momentum != 0.0:
                # First step initializes buf = d (torch/apex semantics:
                # momentum_buffer starts as a clone of d, not 0-decayed).
                new_buf = jnp.where(first, d, momentum * buf + (1.0 - dampening) * d)
                step = d + momentum * new_buf if nesterov else new_buf
            else:
                new_buf = buf
                step = d
            if wd_after_momentum:
                step = step + weight_decay * p32
            return (-step_lr * step).astype(p.dtype), new_buf

        flat = tree_map(leaf, grads, params, state.momentum_buffer)
        is_pair = lambda x: isinstance(x, tuple)
        updates = tree_map(lambda t: t[0], flat, is_leaf=is_pair)
        bufs = tree_map(lambda t: t[1], flat, is_leaf=is_pair)
        return updates, FusedSGDState(count, bufs)

    return optax.GradientTransformation(init, update)

"""apex_tpu — TPU-native training-utilities framework with the capabilities of NVIDIA Apex.

A from-scratch JAX/XLA/Pallas re-design (NOT a port) of the reference stack
(``/root/reference``, NVIDIA Apex): declarative mixed-precision policies
(O0-O3 semantics — ref ``apex/amp/frontend.py:102-193``), fused optimizers
(ref ``apex/optimizers/``), fused normalization / softmax / attention kernels
(ref ``csrc/``), data-parallel gradient sync + synchronized batch norm
(ref ``apex/parallel/``), and Megatron-style tensor/pipeline parallelism
(ref ``apex/transformer/``) — all expressed as mesh programs, functional
transforms, and Pallas kernels instead of CUDA extensions and monkey-patching.

Layering (mirrors SURVEY.md §1's layer map, re-drawn for TPU):

=====  =============================  ==========================================
Layer  apex_tpu module                Reference analogue
=====  =============================  ==========================================
L0     ``apex_tpu.ops``               ``csrc/`` CUDA kernels → Pallas / XLA
L1     ``apex_tpu.ops.multi_tensor``  ``apex/multi_tensor_apply``
L2     ``apex_tpu.amp``               ``apex/amp`` (+ ``apex/fp16_utils``)
L3     ``apex_tpu.optimizers``,       ``apex/optimizers``, ``apex/normalization``,
       ``.normalization``, ``.mlp``,  ``apex/mlp``, ``apex/fused_dense``
       ``.fused_dense``
L4     ``apex_tpu.parallel``          ``apex/parallel`` (DDP, SyncBN, LARC)
L4.5   ``apex_tpu.comm``              — (north-star: compressed collectives,
                                      int8+EF quantized allreduce)
L4.7   ``apex_tpu.fsdp``              — (north-star: ZeRO-3 parameter
                                      sharding — gather-on-demand custom
                                      VJPs, overlapped gather rings,
                                      shard-only optimizer; configured via
                                      ``parallel.ParallelismPlan``)
L5     ``apex_tpu.transformer``       ``apex/transformer`` (TP/PP runtime)
L6     ``apex_tpu.contrib``           ``apex/contrib``
L7     ``apex_tpu.profiler``          ``apex/pyprof``
L7.5   ``apex_tpu.monitor``           — (north-star: unified in-graph
                                      telemetry — metric pytrees, spans,
                                      JSONL sink, MFU report)
L8     ``apex_tpu.resilience``        — (north-star: fault tolerance —
                                      anomaly guard, atomic/async
                                      checkpointing, preemption handling,
                                      chaos harness)
L9     ``apex_tpu.serve``             — (north-star: continuous-batching
                                      inference engine — paged KV cache,
                                      q_len=1 Pallas decode attention,
                                      in-graph sampling, bucketed prefill)
L10    ``apex_tpu.analyze``           — (north-star: compiled-program
                                      contract checker — donation /
                                      recompile / dtype-leak / exposed-
                                      collective / host-sync analyzers on
                                      jaxprs + compiled HLO, plus the
                                      baseline-gated repo graph-lint)
=====  =============================  ==========================================
"""

from apex_tpu._logging import get_logger, RankInfoFormatter  # noqa: F401
from apex_tpu import config  # noqa: F401

__version__ = "0.1.0"

__all__ = [
    "amp",
    "analyze",
    "comm",
    "config",
    "contrib",
    "fp16_utils",
    "fsdp",
    "fused_dense",
    "get_logger",
    "mlp",
    "monitor",
    "normalization",
    "ops",
    "optimizers",
    "parallel",
    "profiler",
    "resilience",
    "serve",
    "transformer",
    "RankInfoFormatter",
]


def __getattr__(name):
    # Lazy subpackage import: keeps `import apex_tpu` cheap and avoids import
    # cycles (the reference does conditional imports in apex/__init__.py:13-20;
    # here nothing is conditional — every subsystem is pure JAX + optional
    # Pallas/C++ with graceful fallbacks).
    if name in __all__:
        import importlib

        try:
            return importlib.import_module(f"apex_tpu.{name}")
        except ModuleNotFoundError as e:
            raise AttributeError(
                f"module 'apex_tpu' has no attribute {name!r} ({e})"
            ) from e
    raise AttributeError(f"module 'apex_tpu' has no attribute {name!r}")

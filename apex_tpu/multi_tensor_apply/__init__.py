"""multi_tensor_apply — API-parity shim (ref ``apex/multi_tensor_apply``).

Reference: ``MultiTensorApply.__call__`` (``multi_tensor_apply.py:24-30``)
dispatches an ``amp_C`` CUDA kernel over chunked tensor lists with a shared
overflow flag — the fused-sweep machinery every apex optimizer rides on.

TPU re-design: the capability (one fused pass over all params) is what XLA
does to a jitted ``tree_map``; there is nothing to chunk. This shim keeps
the call shape for ported code: ``op`` is a per-leaf function, tensor lists
are pytrees, and the "noop flag" becomes a returned all-finite check.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp


class MultiTensorApply:
    """``applier = MultiTensorApply(2048*32); applier(op, noop_flag, lists)``
    (the chunk size is accepted and ignored — XLA fuses globally)."""

    def __init__(self, chunk_size: int = 2048 * 32):
        self.chunk_size = chunk_size

    def __call__(self, op: Callable, noop_flag_or_none: Optional[Any],
                 tensor_lists, *args):
        """Apply ``op(*leaves, *args)`` across the zipped pytrees in
        ``tensor_lists``. Returns ``(results, found_inf)`` where found_inf
        is a f32 0/1 scalar over every INPUT leaf (the overflow-flag
        contract of ``multi_tensor_scale``)."""
        outs = jax.tree_util.tree_map(lambda *ls: op(*ls, *args),
                                      *tensor_lists)
        leaves = [l for t in tensor_lists
                  for l in jax.tree_util.tree_leaves(t)]
        if leaves:
            finite = jnp.stack(
                [jnp.all(jnp.isfinite(l)) for l in leaves]).all()
        else:
            finite = jnp.asarray(True)
        return outs, (~finite).astype(jnp.float32)


multi_tensor_applier = MultiTensorApply()

__all__ = ["MultiTensorApply", "multi_tensor_applier"]

"""Autocast interop helpers (ref ``apex/_autocast_utils.py:6-23``)."""

from __future__ import annotations

from typing import Any, Optional, Sequence

import jax.numpy as jnp


def _get_autocast_dtypes() -> Sequence[Any]:
    """Supported half dtypes, preferred first (ref :6-10 — [bf16, fp16] when
    bf16 is supported). On TPU bf16 is always supported."""
    return [jnp.bfloat16, jnp.float16]


def _get_current_dtype(dtype: Optional[Any] = None) -> Any:
    """Ref :13-16: the active autocast dtype; here, caller-supplied or bf16."""
    return jnp.bfloat16 if dtype is None else dtype


def _cast_if_autocast_enabled(*args, dtype=jnp.bfloat16):
    """Ref :19-23: cast float args to the autocast dtype (always 'enabled' —
    jax has no thread-local autocast; policies are explicit)."""
    return tuple(
        a.astype(dtype)
        if hasattr(a, "dtype") and jnp.issubdtype(a.dtype, jnp.floating)
        else a
        for a in args
    )

"""Generate the per-module API reference (docs/api/*.md).

The reference ships a Sphinx site (``/root/reference/docs/source/*.rst`` —
amp, optimizers, parallel, layernorm, fp16_utils pages built from
docstrings); this repo's equivalent is a docstring-driven markdown tree so
the docs never drift from the code: every public module under ``apex_tpu``
gets one page listing its public classes/functions with signatures and
docstrings (which already carry the reference file:line citations).

Run: ``python docs/generate_api.py`` (rewrites docs/api/). CI-free repo:
regenerate whenever the public surface changes; test_misc_subsystems
checks the tree is importable either way.
"""

from __future__ import annotations

import importlib
import inspect
import os
import pkgutil
import re
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)
OUT = os.path.join(ROOT, "docs", "api")

# pages grouped to mirror the reference's Sphinx toctree (amp, optimizers,
# parallel, layernorm/normalization, fp16_utils) plus the TPU-native
# additions the reference has no page for
REF_PAGE = {
    "apex_tpu.amp": "amp.rst",
    "apex_tpu.fp16_utils": "fp16_utils.rst",
    "apex_tpu.optimizers": "optimizers.rst",
    "apex_tpu.normalization": "layernorm.rst",
    "apex_tpu.parallel": "parallel.rst",
}


def public_symbols(mod):
    names = getattr(mod, "__all__", None)
    if names is None:
        names = [n for n, obj in vars(mod).items()
                 if not n.startswith("_")
                 and getattr(obj, "__module__", None) == mod.__name__
                 and (inspect.isclass(obj) or inspect.isfunction(obj))]
    out = []
    for n in names:
        try:
            obj = getattr(mod, n, None)
        except Exception:  # lazy __getattr__ may raise ImportError, which
            continue       # getattr's default does not suppress
        if obj is not None and (inspect.isclass(obj)
                                or inspect.isfunction(obj)):
            out.append((n, obj))
    return out


def _mask_addresses(text: str) -> str:
    # object-repr defaults (flax _Sentinel, bound functions) stringify
    # with the process's heap address — mask it or every regeneration
    # dirties unrelated pages and buries real API changes in churn
    return re.sub(r" at 0x[0-9a-fA-F]+", " at 0x...", text)


def signature_of(obj) -> str:
    try:
        sig = str(inspect.signature(obj))
    except (ValueError, TypeError):
        return "(...)"
    return _mask_addresses(sig)


def render_module(modname: str) -> str | None:
    try:
        mod = importlib.import_module(modname)
    except Exception:  # unimportable here (e.g. newer-jax-only module on a
        return None    # stock-jax box) — keep the existing page instead
    syms = public_symbols(mod)
    doc = inspect.getdoc(mod) or ""
    if not syms and not doc:
        return None
    lines = [f"# `{modname}`", ""]
    if modname in REF_PAGE:
        lines += [f"*Reference Sphinx page: `docs/source/{REF_PAGE[modname]}`*",
                  ""]
    if doc:
        lines += [doc, ""]
    for name, obj in syms:
        kind = "class" if inspect.isclass(obj) else "def"
        lines += [f"## `{kind} {name}{signature_of(obj)}`", ""]
        odoc = inspect.getdoc(obj)
        if odoc:
            lines += [_mask_addresses(odoc), ""]
        if inspect.isclass(obj):
            for mname, meth in sorted(vars(obj).items()):
                if mname.startswith("_") and mname != "__call__":
                    continue
                if not (inspect.isfunction(meth)
                        or isinstance(meth, (classmethod, staticmethod))):
                    continue
                fn = meth.__func__ if isinstance(
                    meth, (classmethod, staticmethod)) else meth
                lines += [f"### `{name}.{mname}{signature_of(fn)}`", ""]
                mdoc = inspect.getdoc(fn)
                if mdoc:
                    lines += [_mask_addresses(mdoc), ""]
    return "\n".join(lines) + "\n"


def _first_prose_line(text: str) -> str:
    for line in text.splitlines():
        if line and not line.startswith("#") and not line.startswith("*"):
            return line.strip()
    return ""


def _module_exists(modname: str) -> bool:
    """Whether the module's source file exists, WITHOUT importing it (an
    import may fail here precisely for the modules whose pages we keep).
    Used to drop pages of renamed/deleted modules."""
    rel = os.path.join(ROOT, *modname.split("."))
    return (os.path.isfile(rel + ".py")
            or os.path.isfile(os.path.join(rel, "__init__.py")))


def main() -> None:
    """Regenerate every page this interpreter can import; pages for modules
    that fail to import here (e.g. mesh modules needing a newer jax than a
    doc-building box carries) are left as previously generated, so a
    degraded environment can still ADD pages without destroying the rest;
    pages whose module source no longer exists (rename/delete) are removed.
    The index is rebuilt from every page present."""
    os.makedirs(OUT, exist_ok=True)
    for f in os.listdir(OUT):
        if (f.endswith(".md") and f != "index.md"
                and not _module_exists(f[:-3])):
            os.remove(os.path.join(OUT, f))
    import apex_tpu

    modules = ["apex_tpu"]
    for info in pkgutil.walk_packages(apex_tpu.__path__, "apex_tpu."):
        base = info.name.rsplit(".", 1)[-1]
        if base.startswith("_") and base != "__init__":
            continue
        modules.append(info.name)

    rendered = 0
    for modname in sorted(set(modules)):
        text = render_module(modname)
        if text is None:
            continue
        with open(os.path.join(OUT, f"{modname}.md"), "w") as f:
            f.write(text)
        rendered += 1

    index = ["# apex_tpu API reference", "",
             "Generated by `docs/generate_api.py` from the live docstrings "
             "(every entry cites its reference counterpart file:line where "
             "one exists). Reference Sphinx pages map as:", ""]
    for mod, page in REF_PAGE.items():
        index.append(f"- `{page}` → [`{mod}`]({mod}.md)")
    index += ["", "## Modules", ""]
    pages = sorted(f for f in os.listdir(OUT)
                   if f.endswith(".md") and f != "index.md")
    for page in pages:
        modname = page[:-3]
        with open(os.path.join(OUT, page)) as f:
            first = _first_prose_line(f.read())
        index.append(f"- [`{modname}`]({modname}.md) — {first}")
    with open(os.path.join(OUT, "index.md"), "w") as f:
        f.write("\n".join(index) + "\n")
    print(f"re-rendered {rendered} pages; indexed {len(pages)} in {OUT}")


if __name__ == "__main__":
    main()

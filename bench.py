"""Benchmark harness — prints ONE JSON line with the headline metric.

Headline: flagship GPT-2 124M-class bf16 **training step** (fwd + bwd +
FusedAdam) tokens/s on one chip. ``vs_baseline`` is measured MFU divided by
the driver-assigned 0.70 MFU target (BASELINE.json: the reference publishes
no numbers — see BASELINE.md — so the target ratio is the honest comparator).

Run: ``python bench.py`` (uses the real TPU chip when available; falls back
to CPU with the same protocol, flagged in the metric name).

Timing protocol note: the steps are dispatched asynchronously and the clock
stops only after a scalar host-read of the LAST step's loss — on the axon
tunnel platform ``jax.block_until_ready`` returns before execution finishes,
so a value transfer is the only trustworthy fence (the round-1 recorded
number predates this fix and is optimistic).
"""

from __future__ import annotations

import functools
import json
import os
import time

import jax

from apex_tpu.utils.platform import pin_cpu_if_requested

pin_cpu_if_requested()
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

# v5e peak dense bf16 per chip
PEAK_FLOPS = {"tpu": 197e12, "cpu": 1e12}

BATCH, SEQ = 32, 1024
STEPS = 20


def flagship_config(seq: int = SEQ, **overrides):
    """The benchmark model (GPT-2 124M-class). Shared with
    benchmarks/check_mfu_accounting.py so the cross-check always validates
    the same model bench.py times."""
    from apex_tpu.transformer.testing import GPTConfig

    kw = dict(vocab_size=50304, max_seq=seq, hidden=768, num_layers=12,
              num_heads=12, dtype=jnp.bfloat16)
    kw.update(overrides)
    return GPTConfig(**kw)


_STEP_CACHE: dict = {}


def build_train_step(cfg, batch: int, seq: int):
    """Jitted fwd+bwd+FusedAdam step for ``cfg`` on one chip. Returns
    ``(train_step, params, opt_state, tok, tgt)``. The jitted step is
    cached per (cfg, batch, seq) so re-measuring the auto-tuner's winning
    config reuses its compilation instead of paying a fourth compile."""
    key = (cfg, batch, seq)
    if key in _STEP_CACHE:
        train_step, make_inputs = _STEP_CACHE[key]
        return (train_step, *make_inputs())
    from apex_tpu.optimizers import FusedAdam
    from apex_tpu.parallel.mesh import build_mesh
    from apex_tpu.transformer.pipeline_parallel.schedules.common import (
        replicate_loss,
    )
    from apex_tpu.transformer.testing import (
        gpt_loss,
        gpt_param_specs,
        init_gpt_params,
    )

    mesh = build_mesh(tp=1, pp=1, sp=1, devices=jax.devices()[:1])
    specs = gpt_param_specs(cfg)
    opt = FusedAdam(lr=1e-4)

    def loss_fn(p, tok, tgt):
        def body(p, tok, tgt):
            return replicate_loss(gpt_loss(p, tok, tgt, cfg), mesh,
                                  masked_axis=None)

        return jax.shard_map(body, mesh=mesh, in_specs=(specs, P(), P()),
                             out_specs=P())(p, tok, tgt)

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def train_step(params, opt_state, tok, tgt):
        loss, grads = jax.value_and_grad(loss_fn)(params, tok, tgt)
        updates, opt_state = opt.update(grads, opt_state, params)
        params = jax.tree.map(lambda p, u: p + u, params, updates)
        return params, opt_state, loss

    def make_inputs():
        p = init_gpt_params(jax.random.PRNGKey(0), cfg)
        s = opt.init(p)
        k = jax.random.PRNGKey(1)
        tok = jax.random.randint(k, (batch, seq), 0, cfg.vocab_size)
        return p, s, tok, jnp.roll(tok, -1, axis=1)

    _STEP_CACHE[key] = (train_step, make_inputs)
    return (train_step, *make_inputs())


def _measure(remat: bool, remat_policy: str, batch: int, seq: int,
             steps: int, warm_steps: int = 2, unroll: int = 1,
             **cfg_overrides):
    """(tokens/s, n_params, error) of the flagship train step under one
    config; tokens/s is None when it fails (e.g. OOM with remat off).
    Fresh params each call — donation consumes the previous buffers.
    ``cfg_overrides`` go straight to flagship_config (fused_loss,
    ln_pallas, ...) so A/B sweeps share this one fence/timing protocol."""
    cfg = flagship_config(seq, remat=remat, remat_policy=remat_policy,
                          scan_unroll=unroll, **cfg_overrides)
    train_step, params, opt_state, tok, tgt = build_train_step(
        cfg, batch, seq)
    n_params = sum(x.size for x in jax.tree.leaves(params))
    try:
        # warmup (compile); the float() host-read is the real execution fence
        for _ in range(warm_steps):
            params, opt_state, loss = train_step(params, opt_state, tok, tgt)
        float(loss)

        t0 = time.perf_counter()
        for _ in range(steps):
            params, opt_state, loss = train_step(params, opt_state, tok, tgt)
        float(loss)  # forces the whole donated-params chain
        dt = (time.perf_counter() - t0) / steps
    except Exception as e:  # OOM etc. — config unusable on this chip
        return None, n_params, f"{type(e).__name__}: {str(e)[:200]}"
    return batch * seq / dt, n_params, None


def _read_banked_watch():
    """Parsed BENCH_watch.json (the watcher's banked headline) or None —
    one reader for both the sweep-seeding and the dead-tunnel
    evidence-attach paths."""
    try:
        with open(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                               "BENCH_watch.json")) as f:
            return json.load(f)
    except Exception:
        return None


def main() -> None:
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true",
                    help="skip the auto-tune sweep: time the last-known-good"
                         " config with few steps (the watcher's stage-1 shot"
                         " that must bank a number inside a short tunnel"
                         " window)")
    ap.add_argument("--out", default=None,
                    help="also persist the JSON line to this path")
    args = ap.parse_args()

    from apex_tpu.utils.platform import pin_cpu_if_tunnel_dead

    # fall back to the CPU protocol (flagged metric name) instead of
    # hanging the driver on a dead tunnel
    pin_cpu_if_tunnel_dead()
    backend = jax.default_backend()
    on_tpu = backend == "tpu"
    batch, seq, steps = (BATCH, SEQ, STEPS) if on_tpu else (2, 128, 3)

    if args.quick:
        # Last-known-good config (ran on the real chip in round 1):
        # guaranteed-fit remat-full at the full batch. One compile, short
        # timed run.
        candidates = [(batch, True, "full", 1, True)]
        steps = min(steps, 8)
    else:
        # Auto-tune (batch, remat, scan_unroll) jointly: no-remat and
        # selective ("dots") avoid recompute flops the MFU accounting does
        # not credit but may not fit HBM at the full batch; a smaller batch
        # with remat OFF can beat a bigger batch paying recompute (tokens/s
        # is batch-fair); unrolling the layer scan gives XLA straight-line
        # HLO to fuse across layer boundaries at ~12x the layer-compile
        # cost. Measure each briefly and keep the fastest.
        # Ordered most-promising-first so the time budget (below) and the
        # per-candidate provisional banking degrade gracefully. Double
        # batch amortizes fixed per-step cost; OOM is caught and skipped,
        # so probing above the estimated HBM fit only costs its compile.
        # the trailing bool is GPTConfig.fused_loss: the Pallas fused
        # LM-head+CE avoids the 3.2 GB logits but its matmul must keep up
        # with XLA's near-peak native head matmul — the sweep answers it
        # empirically rather than assuming the kernel wins
        candidates = [(batch, False, "full", 1, True),
                      (batch, False, "full", 1, False),
                      (batch * 2, False, "full", 1, True),
                      (batch, True, "dots_attn", 1, True),
                      (batch, True, "dots", 1, True),
                      (batch, False, "full", 12, True),
                      (batch * 2, True, "dots_attn", 1, True),
                      (batch, True, "dots", 12, True),
                      (batch, True, "full", 1, False),
                      (batch * 2, True, "dots", 1, True),
                      (batch, True, "full", 1, True),
                      (batch // 2, False, "full", 1, True)]
        # the watcher's banked winner (BENCH_watch.json tuned_config) goes
        # first: when the staged watcher already tuned on this chip, the
        # sweep opens with the known-best config and the budget spends the
        # rest confirming rather than rediscovering
        banked = _read_banked_watch()
        tc = (banked or {}).get("tuned_config")
        if tc:
            try:
                cand = (tc["batch"], tc["remat"], tc["policy"],
                        tc.get("scan_unroll", 1), tc.get("fused", True))
                if cand in candidates:
                    candidates.remove(cand)
                candidates.insert(0, cand)
            except Exception:
                pass
    if not on_tpu:
        # CPU canary: ONE pinned config, fused=False. The Pallas fused
        # LM-head runs in interpret mode on CPU (~17% slower than XLA's
        # native head matmul here) and its cost is a property of the
        # fallback environment, not the TPU code under test — r04 let the
        # r04-new fused flag default on and the canary silently dropped
        # 67.9 -> 56.0 tokens/s. Pinning keeps round-over-round CPU
        # numbers comparable; the fused-vs-unfused question is answered
        # on the chip by the real sweep above.
        candidates = [(batch, True, "full", 1, False)]
    import sys

    def emit(tokens_per_s, batch, remat, policy, unroll, fused,
             provisional):
        from apex_tpu.monitor import gpt_analytic_flops_per_token, json_record

        cfg = flagship_config(seq)
        # the analytic constant is shared with monitor.report so
        # check_mfu_accounting.py always validates the number divided here
        fpt = gpt_analytic_flops_per_token(
            n_params, cfg.num_layers, cfg.hidden, seq)
        mfu = tokens_per_s * fpt / PEAK_FLOPS.get(backend, 1e12)
        name = "gpt2_124m_bf16_train_tokens_per_sec_chip"
        if not on_tpu:
            name += "_CPU_FALLBACK"
        rec = {
            "metric": name,
            "value": round(tokens_per_s, 1),
            "unit": "tokens/s",
            "vs_baseline": round(mfu / 0.70, 4),
            "tuned_config": {"batch": batch, "remat": remat,
                             "policy": policy, "scan_unroll": unroll,
                             "fused": fused},
        }
        if provisional:
            rec["provisional"] = True  # best-so-far from the short sweep
        if not on_tpu:
            # dead-tunnel run: attach the last banked real-chip headline
            # (benchmarks/tpu_watch.sh stages it) so the CPU-fallback line
            # still carries the round's actual TPU evidence
            banked = _read_banked_watch()
            if banked and "CPU_FALLBACK" not in banked.get("metric", ""):
                rec["last_real_tpu"] = banked
        line = json_record(**rec)
        if args.out:
            with open(args.out, "w") as f:
                f.write(line + "\n")
        return line

    # Candidate-phase time budget: compiles on the tunnel are slow and the
    # caller (driver or watcher) may enforce its own timeout — stop trying
    # new candidates past the budget and finalize with the best so far,
    # so the ONE-JSON-line contract survives any cap >= budget + ~3 min.
    budget_s = float(os.environ.get("APEX_TPU_BENCH_BUDGET_S", "600"))
    t_start = time.perf_counter()

    best, best_tps, n_params, last_err = None, 0.0, 0, None
    for cand_batch, remat, policy, unroll, fused in candidates:
        if best is not None and time.perf_counter() - t_start > budget_s:
            print(f"# sweep budget ({budget_s:.0f}s) reached, finalizing "
                  f"with best so far", file=sys.stderr, flush=True)
            break
        tps, n_params, err = _measure(remat, policy, cand_batch, seq,
                                      steps=3 if on_tpu else 1,
                                      unroll=unroll, fused_loss=fused)
        # per-candidate line on stderr: one tunnel window yields the whole
        # tuning picture even if a later candidate hangs the run
        print(f"# candidate batch={cand_batch} remat={remat}/{policy} "
              f"unroll={unroll} fused={fused}: "
              + (f"{tps:.1f} tokens/s" if tps is not None else f"FAIL {err}"),
              file=sys.stderr, flush=True)
        if err is not None:
            last_err = (f"batch={cand_batch} remat={remat}/{policy} "
                        f"unroll={unroll} fused={fused}: {err}")
        if tps is not None and tps > best_tps:
            best, best_tps = (cand_batch, remat, policy, unroll, fused), tps
            # bank the best-so-far to --out: a timeout mid-sweep (the
            # watcher's staged-fire contract) still leaves a real number
            emit(best_tps, cand_batch, remat, policy, unroll, fused,
                 provisional=True)

    if best is None:
        raise RuntimeError(f"no bench config ran successfully; last error: "
                           f"{last_err}")
    batch, remat, policy, unroll, fused = best
    tokens_per_s, n_params, err = _measure(remat, policy, batch, seq, steps,
                                           unroll=unroll, fused_loss=fused)
    if tokens_per_s is None:
        raise RuntimeError(f"selected config {best} failed the timed run: "
                           f"{err}")
    # standard MFU accounting: 6N per token (fwd+bwd) + causal attention
    # 6*L*hidden*seq per token; remat recompute is NOT credited. Cross-
    # checked against XLA HLO cost analysis by check_mfu_accounting.py.
    print(emit(tokens_per_s, batch, remat, policy, unroll, fused,
               provisional=False))


if __name__ == "__main__":
    main()
